"""Plan-fingerprint cache + learned per-plan policy A/B (ISSUE 18).

Three entry points:

* :func:`run_cache_bench` — the BENCH_SUITE leg: a zipf-distributed
  query mix (a hot quartile of templates dominates the stream, the tail
  appears once or twice) submitted to a real standalone cluster with
  ``ballista.cache.enabled`` off vs on, IDENTICAL inputs and submission
  order.  Result identity is enforced per template with a sha256 row
  fingerprint (PR 10 methodology); the record reports the hot-repeat
  speedup (repeat submissions of an already-seen plan vs the same
  submissions on the cache-less leg) and the measured hit rate.

* :func:`run_policy_bench` — the self-tuning leg: a barrier-dominated
  workload (manufactured straggler map task + reduce-side latency, the
  ISSUE 15 methodology) submitted repeatedly with all-default settings
  vs ``ballista.cache.policy.enabled=true``.  The first policy-leg run
  executes at baseline and the doctor's ``barrier_dominated_job``
  finding teaches the store ``ballista.shuffle.pipelined=true``; later
  runs apply it and their median must beat the all-defaults median.

* :func:`run_plan_cache_smoke` — the tier-1 ``--bench-smoke`` gate:
  tiny inputs; asserts the repeat submission serves from cache with
  zero dispatched tasks and bit-identical rows, that re-registering
  different data invalidates the match (fresh, correct results), and
  that the knob-off leg never consults the cache.

Every query carries a run-unique tag inside a predicate literal so
fingerprints never collide across bench invocations (the standalone
scheduler's plan cache lives in a shared work dir and persists).
"""

from __future__ import annotations

import hashlib
import random
import time
import uuid

import pyarrow as pa

BASE_CONFIG = {
    "ballista.mesh.enable": "false",
    "ballista.tpu.min_rows": "0",
    "ballista.shuffle.partitions": "4",
}


def _fingerprint(table: pa.Table) -> str:
    rows = sorted(zip(*[c.to_pylist() for c in table.columns]))
    h = hashlib.sha256()
    for row in rows:
        h.update(repr(row).encode())
    return h.hexdigest()


def _table(n_rows: int, groups: int = 23) -> pa.Table:
    return pa.table(
        {
            "g": pa.array(
                [f"g{i % groups}" for i in range(n_rows)], pa.string()
            ),
            "x": pa.array(
                [float(i % 251) for i in range(n_rows)], pa.float64()
            ),
        }
    )


def _open_ctx(extra_config: dict, table: pa.Table, num_executors: int = 2):
    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.config import BallistaConfig
    from arrow_ballista_tpu.context import MemoryTable

    cfg = dict(BASE_CONFIG)
    cfg.update(extra_config)
    ctx = BallistaContext.standalone(
        config=BallistaConfig(cfg),
        num_executors=num_executors,
        concurrent_tasks=4,
    )
    ctx.register_table("t", MemoryTable.from_table(table, 4))
    return ctx


def _cache_counters(ctx) -> dict:
    scheduler, _ = ctx._standalone_handles
    snap = scheduler.server.state.plan_cache.snapshot()
    return {k: snap[k] for k in ("hits", "misses", "stores", "evictions")}


def _zipf_sequence(
    n_templates: int, n_submits: int, seed: int
) -> list[int]:
    """Zipf-ish template stream: weight 1/(k+1), so the first quartile
    of templates dominates the submissions."""
    rng = random.Random(seed)
    weights = [1.0 / (k + 1) for k in range(n_templates)]
    seq = rng.choices(range(n_templates), weights=weights, k=n_submits)
    # make sure every template appears at least once (the cold tail)
    for k in range(n_templates):
        if k not in seq:
            seq[rng.randrange(n_submits)] = k
    return seq


def run_cache_bench(
    n_rows: int = 300_000,
    n_templates: int = 8,
    n_submits: int = 24,
    seed: int = 18,
) -> dict:
    tag = uuid.uuid4().hex[:8]
    templates = [
        f"select g, sum(x) as s, count(x) as n from t "
        f"where g <> '{tag}-none' and x > {k} group by g"
        for k in range(n_templates)
    ]
    seq = _zipf_sequence(n_templates, n_submits, seed)
    table = _table(n_rows)

    def leg(cache_on: bool):
        ctx = _open_ctx(
            {"ballista.cache.enabled": "true" if cache_on else "false"},
            table,
        )
        try:
            before = _cache_counters(ctx)
            walls, shas = [], {}
            for k in seq:
                t0 = time.perf_counter()
                result = ctx.sql(templates[k]).collect()
                walls.append(time.perf_counter() - t0)
                sha = _fingerprint(result)
                assert shas.setdefault(k, sha) == sha, (
                    f"template {k} row fingerprint drifted within leg"
                )
            after = _cache_counters(ctx)
            counters = {k: after[k] - before[k] for k in after}
            return walls, shas, counters
        finally:
            ctx.close()

    walls_off, shas_off, _ = leg(False)
    walls_on, shas_on, counters = leg(True)
    assert shas_off == shas_on, "cache leg changed query results"

    seen: set = set()
    repeat_idx = []
    for i, k in enumerate(seq):
        if k in seen:
            repeat_idx.append(i)
        seen.add(k)
    assert repeat_idx, "zipf stream produced no repeats"
    hot_off = sum(walls_off[i] for i in repeat_idx) / len(repeat_idx)
    hot_on = sum(walls_on[i] for i in repeat_idx) / len(repeat_idx)
    speedup = hot_off / hot_on if hot_on > 0 else float("inf")
    lookups = counters["hits"] + counters["misses"]
    hit_rate = counters["hits"] / lookups if lookups else 0.0
    return {
        "metric": "plan_cache_hot_speedup",
        "value": round(speedup, 2),
        "unit": "x repeat-submission speedup",
        "vs_baseline": round(speedup, 3),
        "hit_rate": round(hit_rate, 3),
        "submits": n_submits,
        "templates": n_templates,
        "repeat_submits": len(repeat_idx),
        "hot_repeat_mean_s_off": round(hot_off, 4),
        "hot_repeat_mean_s_on": round(hot_on, 4),
        "wall_total_s_off": round(sum(walls_off), 3),
        "wall_total_s_on": round(sum(walls_on), 3),
        "counters": counters,
        "result_identity": "sha256 row fingerprints equal across legs",
    }


def _run_barrier_job(ctx, sql, straggler_ms: int, reduce_delay_ms: int):
    from arrow_ballista_tpu.testing import faults

    if straggler_ms:
        faults.arm(
            "task.run",
            times=1,
            action="delay",
            delay_ms=straggler_ms,
            match=lambda stage_id=0, partition_id=0, speculative=False, **_:
                stage_id == 1 and partition_id == 1 and not speculative,
        )
    if reduce_delay_ms:
        faults.arm(
            "task.run",
            times=-1,
            action="delay",
            delay_ms=reduce_delay_ms,
            match=lambda stage_id=0, **_: stage_id == 2,
        )
    try:
        t0 = time.perf_counter()
        result = ctx.sql(sql).collect()
        return time.perf_counter() - t0, _fingerprint(result)
    finally:
        faults.clear()


def run_policy_bench(
    n_rows: int = 40_000,
    repeats: int = 5,
    straggler_ms: int = 900,
    reduce_delay_ms: int = 300,
) -> dict:
    import statistics

    tag = uuid.uuid4().hex[:8]
    sql = (
        f"select g, sum(x) as s, count(x) as n from t "
        f"where g <> '{tag}-none' group by g"
    )
    table = _table(n_rows)

    def leg(policy_on: bool):
        extra = (
            {
                "ballista.cache.policy.enabled": "true",
                "ballista.cache.policy.shadow_fraction": "0",
            }
            if policy_on
            else {}
        )
        ctx = _open_ctx(extra, table)
        walls, shas = [], set()
        try:
            for _ in range(repeats):
                wall, sha = _run_barrier_job(
                    ctx, sql, straggler_ms, reduce_delay_ms
                )
                # the scheduler records findings on completion; drain so
                # the next submit sees what this one learned
                scheduler, _ = ctx._standalone_handles
                scheduler.server.drain()
                walls.append(wall)
                shas.add(sha)
            assert len(shas) == 1, "policy leg changed query results"
            snap = scheduler.server.state.policy_store.snapshot()
            return walls, shas.pop(), snap
        finally:
            ctx.close()

    walls_def, sha_def, _ = leg(False)
    walls_pol, sha_pol, snap = leg(True)
    assert sha_def == sha_pol, "policy overrides changed query results"

    learned = {}
    for row in snap.get("plans", []):
        learned.update(row.get("overrides") or {})
    assert learned.get("ballista.shuffle.pipelined") == "true", (
        f"policy store learned nothing useful: {snap}"
    )
    # run 0 of the policy leg executes at baseline (nothing learned yet);
    # the applied population is every later run
    med_def = statistics.median(walls_def)
    med_applied = statistics.median(walls_pol[1:])
    speedup = med_def / med_applied if med_applied > 0 else float("inf")
    return {
        "metric": "plan_policy_autotune_speedup",
        "value": round(speedup, 2),
        "unit": "x vs all-default settings",
        "vs_baseline": round(speedup, 3),
        "defaults_median_s": round(med_def, 3),
        "applied_median_s": round(med_applied, 3),
        "learned_overrides": learned,
        "repeats": repeats,
        "result_identity": "sha256 row fingerprints equal across legs",
    }


def run_plan_cache_smoke(n_rows: int = 4_000) -> dict:
    """Tier-1 gate: repeat hit with zero dispatched tasks + identical
    rows, snapshot invalidation, knob-off leg untouched."""
    from arrow_ballista_tpu.context import MemoryTable

    tag = uuid.uuid4().hex[:8]
    sql = (
        f"select g, sum(x) as s, count(x) as n from t "
        f"where g <> '{tag}-none' group by g"
    )

    # knob-off leg: two submissions, cache never consulted
    ctx = _open_ctx({"ballista.cache.enabled": "false"}, _table(n_rows))
    try:
        before = _cache_counters(ctx)
        off_shas = {_fingerprint(ctx.sql(sql).collect()) for _ in range(2)}
        delta = {
            k: v - before[k] for k, v in _cache_counters(ctx).items()
        }
        assert len(off_shas) == 1
        assert not any(delta.values()), (
            f"knob-off leg touched the plan cache: {delta}"
        )
    finally:
        ctx.close()

    ctx = _open_ctx({"ballista.cache.enabled": "true"}, _table(n_rows))
    try:
        before = _cache_counters(ctx)
        sha1 = _fingerprint(ctx.sql(sql).collect())
        j1 = sorted(ctx._job_ids)[0]
        sha2 = _fingerprint(ctx.sql(sql).collect())
        (j2,) = [j for j in ctx._job_ids if j != j1]
        assert sha1 == sha2, "cache hit changed query results"
        assert sha1 in off_shas, "cache leg differs from knob-off leg"
        scheduler, _ = ctx._standalone_handles
        scheduler.server.drain()
        tm = scheduler.server.state.task_manager
        d2 = tm.get_job_detail(j2)
        assert d2["state"] == "completed"
        served = [r for r in d2["stages"] if r.get("cache")]
        assert served, f"repeat submit dispatched tasks: {d2['stages']}"
        delta = {
            k: v - before[k] for k, v in _cache_counters(ctx).items()
        }
        assert delta["hits"] >= 1 and delta["stores"] >= 1, delta

        # invalidation: different data under the same name and shape
        # must recompute, not serve the stale entry
        flipped = pa.table(
            {
                "g": _table(n_rows)["g"],
                "x": pa.array(
                    [float((i + 1) % 251) for i in range(n_rows)],
                    pa.float64(),
                ),
            }
        )
        ctx.register_table("t", MemoryTable.from_table(flipped, 4))
        sha3 = _fingerprint(ctx.sql(sql).collect())
        assert sha3 != sha1, "stale cached result served after data change"
        return {
            "hit_stages": [r["stage_id"] for r in served],
            "cache_bytes": sum(
                (r["cache"] or {}).get("bytes", 0) for r in served
            ),
            "counters": delta,
            "invalidated_on_data_change": True,
        }
    finally:
        ctx.close()
