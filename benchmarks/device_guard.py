"""Shared accelerator probe/fallback policy for the bench entry points.

The axon TPU backend can HANG during init (not raise) when the tunnel or
chip is held elsewhere, so the first touch happens in a SUBPROCESS with a
hard timeout; on timeout the probe retries once (transient holds clear in
seconds), and only if the device never comes up does the caller's process
fall back to the host CPU platform.  bench.py and bench_suite.py share
this one policy so their failure behavior cannot drift.
"""

from __future__ import annotations

import subprocess
import sys
import time
from typing import Optional, Tuple


def probe_backend(timeout_s: float) -> Optional[str]:
    """Backend name from a throwaway subprocess, "timeout", or None."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            capture_output=True,
            timeout=timeout_s,
            text=True,
        )
        if p.returncode == 0 and p.stdout.strip():
            return p.stdout.strip().splitlines()[-1]
        return None
    except subprocess.TimeoutExpired:
        return "timeout"
    except Exception:
        return None


def ensure_device() -> Tuple[str, Optional[str]]:
    """(active platform after any fallback, error string or None).

    Must run BEFORE anything imports jax in the calling process.  An
    explicit ``JAX_PLATFORMS=cpu`` is an intentional dev/test platform:
    no probe, no error.
    """
    import os

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        import jax

        # the env var alone is NOT enough: a session-level axon pin wins
        # over it and the first backend touch would hang on the tunnel —
        # the config API is the reliable override
        jax.config.update("jax_platforms", "cpu")
        return jax.default_backend(), None

    probed = probe_backend(180)
    if probed in (None, "timeout"):
        time.sleep(10)
        probed = probe_backend(120)

    import jax

    error = None
    if probed in (None, "timeout", "cpu"):
        error = "device init unavailable (probe=%s)" % probed
        jax.config.update("jax_platforms", "cpu")
    return jax.default_backend(), error
