"""Streaming pipelined execution A/B (ISSUE 15).

Two entry points:

* :func:`run_pipelined_bench` — the BENCH_SUITE leg: a barrier-dominated
  shuffle query (one manufactured straggler map task holds the map stage
  open; the reduce side carries manufactured per-task latency that a
  pipelined scheduler can overlap with the straggler window) measured
  with ``ballista.shuffle.pipelined`` off vs on over a real 2-executor
  standalone cluster on IDENTICAL inputs.  Result identity is enforced
  with a sha256 row fingerprint (PR 10 methodology); the record reports
  wall-clock and the doctor's measured ``barrier_wait`` for both legs —
  the pipelined leg's barrier wait collapsing toward zero is the
  expected signature.

* :func:`run_pipelining_smoke` — the tier-1 ``--bench-smoke`` gate: a
  tiny 2-executor job with one manufactured slow map task, asserting
  the pipelined leg's first reduce dispatch PRECEDES the last map
  commit and that results are bit-identical to the barrier leg.

The manufactured latencies are injection-point delays (``task.run``):
the straggler models a slow map task, the reduce-side delay models
reduce work that exists regardless of scheduling — pipelining wins
exactly when that work overlaps the producer's tail instead of
queueing behind the barrier.
"""

from __future__ import annotations

import hashlib
import time

import pyarrow as pa

BASE_CONFIG = {
    "ballista.mesh.enable": "false",
    "ballista.tpu.min_rows": "0",
    "ballista.shuffle.partitions": "4",
}

SQL = "select g, sum(x) as s, count(x) as n from t group by g"


def _fingerprint(table: pa.Table) -> str:
    rows = sorted(zip(*[c.to_pylist() for c in table.columns]))
    h = hashlib.sha256()
    for row in rows:
        h.update(repr(row).encode())
    return h.hexdigest()


def _stage_timing(detail: dict, sid: int) -> dict:
    for row in detail.get("stages", []):
        if row.get("stage_id") == sid:
            return row.get("timing") or {}
    return {}


def _run_leg(
    pipelined: bool,
    n_rows: int,
    straggler_ms: int,
    reduce_delay_ms: int,
    min_fraction: float = 0.25,
):
    """One standalone A/B leg; returns (fingerprint, wall_s, report,
    detail)."""
    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.config import BallistaConfig
    from arrow_ballista_tpu.context import MemoryTable
    from arrow_ballista_tpu.obs.doctor import job_report
    from arrow_ballista_tpu.testing import faults

    cfg = dict(BASE_CONFIG)
    cfg["ballista.shuffle.pipelined"] = "true" if pipelined else "false"
    cfg["ballista.shuffle.pipelined_min_fraction"] = str(min_fraction)
    ctx = BallistaContext.standalone(
        config=BallistaConfig(cfg), num_executors=2, concurrent_tasks=4
    )
    try:
        ctx.register_table(
            "t",
            MemoryTable.from_table(
                pa.table(
                    {
                        "g": pa.array(
                            [f"g{i % 23}" for i in range(n_rows)], pa.string()
                        ),
                        "x": pa.array(
                            [float(i % 251) for i in range(n_rows)],
                            pa.float64(),
                        ),
                    }
                ),
                4,
            ),
        )
        if straggler_ms:
            faults.arm(
                "task.run",
                times=1,
                action="delay",
                delay_ms=straggler_ms,
                match=lambda stage_id=0, partition_id=0, speculative=False, **_:
                    stage_id == 1 and partition_id == 1 and not speculative,
            )
        if reduce_delay_ms:
            faults.arm(
                "task.run",
                times=-1,
                action="delay",
                delay_ms=reduce_delay_ms,
                match=lambda stage_id=0, **_: stage_id == 2,
            )
        t0 = time.perf_counter()
        result = ctx.sql(SQL).collect()
        wall_s = time.perf_counter() - t0
        (job_id,) = ctx._job_ids
        scheduler, _ = ctx._standalone_handles
        scheduler.server.drain()
        detail = scheduler.server.state.task_manager.get_job_detail(job_id)
        report = job_report(detail, [], [])
        return _fingerprint(result), wall_s, report, detail
    finally:
        faults.clear()
        ctx.close()


def run_pipelined_bench(
    n_rows: int = 200_000,
    straggler_ms: int = 3000,
    reduce_delay_ms: int = 1800,
) -> dict:
    """Barrier vs pipelined on identical inputs; returns the bench
    record (``metric: pipelined_stage_speedup``)."""
    fp_b, wall_b, rep_b, _ = _run_leg(
        False, n_rows, straggler_ms, reduce_delay_ms
    )
    fp_p, wall_p, rep_p, detail_p = _run_leg(
        True, n_rows, straggler_ms, reduce_delay_ms
    )
    assert fp_b == fp_p, (
        f"pipelined leg changed the result: {fp_b} != {fp_p}"
    )
    barrier_b = (rep_b["critical_path"].get("breakdown") or {}).get(
        "barrier_wait_ms", 0.0
    )
    barrier_p = (rep_p["critical_path"].get("breakdown") or {}).get(
        "barrier_wait_ms", 0.0
    )
    rows = {r["stage_id"]: r for r in detail_p.get("stages", [])}
    partial = bool(
        (rows.get(2, {}).get("pipeline") or {}).get("partial_start")
    )
    return {
        "metric": "pipelined_stage_speedup",
        "value": round(wall_b / wall_p, 3),
        "unit": "x (barrier wall / pipelined wall)",
        "vs_baseline": round(wall_b / wall_p, 3),
        "barrier_wall_s": round(wall_b, 3),
        "pipelined_wall_s": round(wall_p, 3),
        "barrier_wait_ms_barrier_leg": round(barrier_b, 1),
        "barrier_wait_ms_pipelined_leg": round(barrier_p, 1),
        "barrier_wait_drop_pct": round(
            100.0 * (1.0 - barrier_p / barrier_b), 1
        )
        if barrier_b > 0
        else None,
        "consumer_started_on_partial_input": partial,
        "fingerprint": fp_p,
        "n_rows": n_rows,
        "straggler_ms": straggler_ms,
        "reduce_delay_ms": reduce_delay_ms,
    }


def run_pipelining_smoke(straggler_ms: int = 800) -> dict:
    """Tier-1 ``--bench-smoke`` gate: the pipelined leg's first reduce
    dispatch precedes the last map commit and results are bit-identical
    to the barrier leg.  Assertions run inside; the returned record is
    informational."""
    fp_b, _wall_b, _rep_b, _ = _run_leg(False, 20_000, straggler_ms, 0)
    fp_p, _wall_p, rep_p, detail = _run_leg(True, 20_000, straggler_ms, 0)
    assert fp_b == fp_p, f"pipelined result diverged: {fp_b} != {fp_p}"
    rows = {r["stage_id"]: r for r in detail.get("stages", [])}
    assert (rows.get(2, {}).get("pipeline") or {}).get("partial_start"), (
        "consumer never started on partial input"
    )
    map_fin = _stage_timing(detail, 1).get("finish_us") or {}
    red_disp = _stage_timing(detail, 2).get("dispatch_us") or {}
    assert map_fin and red_disp, "timing anchors missing"
    first_reduce_dispatch = min(red_disp.values())
    last_map_commit = max(map_fin.values())
    assert first_reduce_dispatch < last_map_commit, (
        "pipelined leg's first reduce dispatch did not precede the last "
        f"map commit ({first_reduce_dispatch} >= {last_map_commit})"
    )
    return {
        "results_identical": True,
        "first_reduce_dispatch_before_last_map_commit_ms": round(
            (last_map_commit - first_reduce_dispatch) / 1e3, 1
        ),
        "barrier_wait_ms_pipelined_leg": round(
            (rep_p["critical_path"].get("breakdown") or {}).get(
                "barrier_wait_ms", 0.0
            ),
            1,
        ),
        "fingerprint": fp_p,
    }
