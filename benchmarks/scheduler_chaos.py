"""Scheduler crash/failover chaos soak (ISSUE 20).

Two entry points:

* :func:`run_chaos_bench` — the BENCH_SUITE legs: a burst of DISTINCT
  group-by jobs is submitted to a real scheduler subprocess with
  admission pinned to one-running-job (so a deep queue exists by
  construction), the scheduler is SIGKILLed mid-burst, and the run
  continues through (a) a RESTART of the same process on the same
  state/db/work dirs and (b) a FAILOVER to a live backup scheduler
  sharing the state backend.  Every job must complete with rows
  sha-identical to a local single-process run, the queued backlog must
  be re-admitted in submit order (admission WAL), the autoscaler fleet
  must be ADOPTED rather than relaunched (pid files), and no
  (stage, partition) may be committed twice for one job.  The record
  reports MTTR: SIGKILL → first post-recovery admission dispatch.

* :func:`run_chaos_smoke` — the tier-1 ``--chaos-smoke`` gate: the
  restart leg at small scale with the same assertions.

Everything runs out-of-process (``python -m arrow_ballista_tpu
.scheduler`` + its subprocess executor fleet) because the whole point
is process death: SIGKILL must land on a real pid with no chance to
flush, and recovery must read ONLY what the state backend, the pid
files and the event journal durably recorded.

The numbers are integers-stored-as-float (every sum is exactly
representable), so fingerprints are bit-stable across partition orders,
restarts and schedulers — any mismatch is a real wrong answer, not
float re-association.
"""

from __future__ import annotations

import json
import os
import random
import tempfile
import threading
import time
from collections import Counter
from typing import Dict, List, Optional

import pyarrow as pa

BASE_CONFIG = {
    "ballista.tpu.enable": "false",
    "ballista.mesh.enable": "false",
    "ballista.shuffle.partitions": "2",
    "ballista.client.job_timeout_seconds": "300",
}

N_ROWS = 6000

# min == max: the fleet size is pinned, so any post-kill launch is a
# double-launch bug, not a scale-out — exactly what adoption must prevent
AUTOSCALER_SETTINGS = ",".join(
    [
        "ballista.autoscaler.min_executors=2",
        "ballista.autoscaler.max_executors=2",
        "ballista.autoscaler.scale_out_sustain_seconds=0.5",
        "ballista.autoscaler.cooldown_seconds=1",
        "ballista.autoscaler.scale_in_idle_seconds=3600",
        "ballista.autoscaler.launch_timeout_seconds=90",
    ]
)


def _sql(i: int) -> str:
    # distinct plan per job: a shared-fingerprint burst could mask
    # cross-job result mixups after replay
    return f"select g, sum(x) + {i} as s, count(x) as n from t group by g"


def _table() -> pa.Table:
    return pa.table(
        {
            "g": pa.array([f"g{i % 23}" for i in range(N_ROWS)]),
            "x": pa.array([float(i % 251) for i in range(N_ROWS)]),
        }
    )


def _expected_fingerprints(n_jobs: int) -> List[str]:
    """Ground truth from a local single-process run of every job."""
    from arrow_ballista_tpu.config import BallistaConfig
    from arrow_ballista_tpu.context import SessionContext
    from arrow_ballista_tpu.testing.chaos import fingerprint

    ctx = SessionContext(BallistaConfig(dict(BASE_CONFIG)))
    ctx.register_arrow_table("t", _table(), 2)
    return [fingerprint(ctx.sql(_sql(i)).collect()) for i in range(n_jobs)]


def _scheduler_args(
    backend_args: List[str],
    work_dir: str,
    autoscaler_work_dir: str,
    journal_dir: str,
    executor_timeout_s: int,
) -> List[str]:
    return [
        *backend_args,
        "--scheduler-policy", "push-staged",
        "--work-dir", work_dir,
        "--admission-enabled", "1",
        "--admission-defaults", "ballista.admission.max_running_jobs=1",
        "--admission-wal-enabled", "1",
        "--autoscaler-enabled", "1",
        "--autoscaler-settings", AUTOSCALER_SETTINGS,
        "--autoscaler-executor-slots", "2",
        "--autoscaler-work-dir", autoscaler_work_dir,
        "--autoscaler-heartbeat-seconds", "1.5",
        "--event-journal-dir", journal_dir,
        "--executor-timeout-seconds", str(executor_timeout_s),
    ]


def _submit_burst(ctx, n_jobs: int) -> List[str]:
    return [
        ctx.execute_logical_plan(ctx.sql(_sql(i)).plan) for i in range(n_jobs)
    ]


def _start_waiters(ctx, job_ids: List[str], timeout_s: float):
    """One waiter thread per job, started BEFORE the kill — the waits
    must ride through the outage on the client retry/rotation path."""
    results: Dict[int, dict] = {}
    lock = threading.Lock()

    def wait_one(idx: int, jid: str) -> None:
        try:
            status = ctx.wait_for_job(jid, timeout_s=timeout_s)
            with lock:
                results[idx] = {"status": status}
        except Exception as e:  # noqa: BLE001 - asserted on later
            with lock:
                results[idx] = {"error": repr(e)}

    threads = [
        threading.Thread(target=wait_one, args=(i, jid), name=f"wait-{i}")
        for i, jid in enumerate(job_ids)
    ]
    for th in threads:
        th.start()
    return threads, results


def _wait_journal(
    journal_dir: str, kind: str, n: int, timeout_s: float = 90.0
) -> List[dict]:
    """Poll the on-disk journal until ``n`` events of ``kind`` exist.

    ExecuteQuery acks BEFORE admission runs (submit posts JobQueued to
    the scheduler event loop), so "submit returned" does NOT mean "WAL
    entry written" — the journal is the observable proof the queue
    (and its WAL shadow) actually holds the burst before we kill.
    """
    from arrow_ballista_tpu.testing.chaos import read_journal

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        events = read_journal(journal_dir, kind)
        if len(events) >= n:
            return events
        time.sleep(0.1)
    raise RuntimeError(
        f"journal {journal_dir}: only {len(read_journal(journal_dir, kind))}"
        f" of {n} {kind!r} events within {timeout_s:.0f}s"
    )


def _audit_leg(
    leg: str,
    job_ids: List[str],
    results: Dict[int, dict],
    expected: List[str],
    pre_journal: str,
    post_journal: str,
    t_kill: float,
) -> dict:
    """Shared post-mortem: completion, result identity, replay order,
    duplicate commits, MTTR.  Raises AssertionError on any violation."""
    from arrow_ballista_tpu.testing.chaos import read_journal

    errors = {
        i: r["error"] for i, r in results.items() if "error" in r
    }
    assert not errors, f"{leg}: jobs failed to complete: {errors}"
    assert len(results) == len(job_ids), (
        f"{leg}: {len(job_ids) - len(results)} waiter(s) never returned"
    )

    # result identity + duplicate partition commits, from the final
    # committed output locations
    duplicate_commits = 0
    mismatches = []
    for i, jid in enumerate(job_ids):
        status = results[i]["status"]
        assert status["state"] == "completed", (
            f"{leg}: job {jid} ended {status['state']!r}"
        )
        commits = Counter(
            (loc.partition_id.stage_id, loc.partition_id.partition_id)
            for loc in status.get("locations", [])
        )
        duplicate_commits += sum(c - 1 for c in commits.values() if c > 1)
        fp = results[i]["fp"]
        if fp != expected[i]:
            mismatches.append(jid)
    assert duplicate_commits == 0, (
        f"{leg}: {duplicate_commits} duplicate partition commit(s)"
    )
    assert not mismatches, (
        f"{leg}: result fingerprints diverged from the local run for "
        f"{mismatches}"
    )

    # replay order: every job requeued after the kill must come back in
    # submit order, and every job neither admitted nor finished before
    # the kill must be among them
    admitted_pre = {
        e.get("job")
        for e in read_journal(pre_journal, "job_admitted")
        if e.get("ts", 0) <= t_kill
    }
    requeued = [
        e.get("job")
        for e in read_journal(post_journal, "job_requeued")
        if e.get("ts", 0) > t_kill
    ]
    submit_index = {jid: i for i, jid in enumerate(job_ids)}
    order = [submit_index[j] for j in requeued if j in submit_index]
    assert order == sorted(order), (
        f"{leg}: WAL replay broke submit order: {requeued}"
    )
    expected_requeue = [j for j in job_ids if j not in admitted_pre]
    missing = [j for j in expected_requeue if j not in requeued]
    assert not missing, (
        f"{leg}: queued jobs lost across the crash (never requeued): "
        f"{missing}"
    )

    admitted_post = [
        e.get("ts", 0)
        for e in read_journal(post_journal, "job_admitted")
        if e.get("ts", 0) > t_kill
    ]
    assert admitted_post, f"{leg}: no admission dispatch after the kill"
    return {
        "leg": leg,
        "jobs": len(job_ids),
        "completed": len(job_ids),
        "failed": 0,
        "duplicate_partition_commits": duplicate_commits,
        "requeued": len(requeued),
        "admitted_before_kill": len(admitted_pre),
        "mttr_first_dispatch_s": round(min(admitted_post) - t_kill, 3),
    }


def _fetch_outputs(ctx, job_ids: List[str], results: Dict[int, dict]) -> None:
    from arrow_ballista_tpu.testing.chaos import fingerprint

    for i in range(len(job_ids)):
        if "status" in results.get(i, {}):
            results[i]["fp"] = fingerprint(
                ctx.fetch_job_output(results[i]["status"])
            )


# ------------------------------------------------------------------ legs
def run_restart_leg(
    n_jobs: int = 10,
    task_delay_ms: int = 200,
    seed: int = 7,
    job_timeout_s: float = 240.0,
) -> dict:
    """SIGKILL the only scheduler mid-burst, restart it on the same
    sqlite db + work dirs, and require full recovery: WAL replay in
    order, orphan-fleet adoption (no relaunch), all jobs completing
    sha-identical."""
    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.config import BallistaConfig
    from arrow_ballista_tpu.context import MemoryTable
    from arrow_ballista_tpu.testing.chaos import (
        SchedulerProc,
        free_port,
        kill_orphans,
        read_journal,
    )

    rng = random.Random(seed)
    root = tempfile.mkdtemp(prefix="ballista-chaos-restart-")
    db = os.path.join(root, "state.db")
    wd = os.path.join(root, "plans")
    wd_as = os.path.join(root, "fleet")
    jdir = os.path.join(root, "journal")
    args = _scheduler_args(
        ["--config-backend", "sqlite", "--db-path", db],
        wd, wd_as, jdir, executor_timeout_s=30,
    )
    env = {"BALLISTA_FAULTS": f"task.run:-1:delay={task_delay_ms}"}
    port = free_port()
    expected = _expected_fingerprints(n_jobs)

    sched = SchedulerProc(
        port, free_port(), args=args, env=env,
        log_path=os.path.join(root, "scheduler-a.log"),
    )
    sched2: Optional[SchedulerProc] = None
    try:
        sched.wait_ready()
        sched.wait_alive_executors(2)
        ctx = BallistaContext.remote(
            "127.0.0.1", port, BallistaConfig(dict(BASE_CONFIG))
        )
        ctx.register_table("t", MemoryTable.from_table(_table(), 2))
        job_ids = _submit_burst(ctx, n_jobs)
        threads, results = _start_waiters(ctx, job_ids, job_timeout_s)

        # the kill gate: the whole burst durably queued, at least one
        # job dispatched, then a seeded mid-execution jitter
        _wait_journal(jdir, "job_queued", n_jobs)
        _wait_journal(jdir, "job_admitted", 1)
        time.sleep(rng.uniform(0.3, 0.9))
        t_kill = sched.kill()

        sched2 = SchedulerProc(
            port, sched.rest_port, args=args, env=env,
            log_path=os.path.join(root, "scheduler-b.log"),
        )
        sched2.wait_ready()
        t_ready = time.time()
        for th in threads:
            th.join(timeout=job_timeout_s + 30)
        _fetch_outputs(ctx, job_ids, results)

        record = _audit_leg(
            "restart", job_ids, results, expected, jdir, jdir, t_kill
        )
        record["scheduler_ready_s"] = round(t_ready - t_kill, 3)

        # adoption, not relaunch: the restarted scheduler must report
        # the SAME two executors alive and the journal must show an
        # adopt decision with zero post-kill launches
        adopts = [
            e for e in read_journal(jdir, "autoscale_decision")
            if e.get("action") == "adopt" and e.get("ts", 0) > t_kill
        ]
        assert adopts, "restart: no orphan-adoption decision in journal"
        # adopted children re-register and flip ALIVE (journalled with
        # adopted=true); any NON-adopted launch after the kill is a
        # duplicate fleet
        launches_post = [
            e for e in read_journal(jdir, "executor_launched")
            if e.get("ts", 0) > t_kill and not e.get("adopted")
        ]
        assert not launches_post, (
            f"restart: double-launch storm after adoption: {launches_post}"
        )
        state = sched2.rest_get("/api/state")
        alive = sum(1 for e in state["executors"] if e["alive"])
        assert alive == 2, f"restart: expected 2 alive executors, saw {alive}"
        record["adopted_executors"] = len(adopts[0].get("executors", []))
        record["post_kill_launches"] = 0
        ctx.close()
        return record
    finally:
        for s in (sched2, sched):
            if s is not None:
                try:
                    s.stop()
                except Exception:  # noqa: BLE001 - cleanup
                    pass
        kill_orphans(wd_as)


def run_takeover_leg(
    n_jobs: int = 10,
    task_delay_ms: int = 200,
    seed: int = 11,
    job_timeout_s: float = 240.0,
) -> dict:
    """SIGKILL the primary mid-burst with a live backup sharing the
    state backend: the client rotates endpoints, the backup declares the
    primary dead, adopts its jobs, replays its admission WAL and runs
    the backlog to completion on its own fleet."""
    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.config import BallistaConfig
    from arrow_ballista_tpu.context import MemoryTable
    from arrow_ballista_tpu.scheduler.backend import MemoryBackend
    from arrow_ballista_tpu.scheduler.kvstore import KvStoreHandle
    from arrow_ballista_tpu.testing.chaos import (
        SchedulerProc,
        free_port,
        kill_orphans,
    )

    rng = random.Random(seed)
    root = tempfile.mkdtemp(prefix="ballista-chaos-takeover-")
    kv = KvStoreHandle(MemoryBackend(), "127.0.0.1", 0).start()
    dirs = {
        name: os.path.join(root, name)
        for name in ("plans-a", "fleet-a", "journal-a",
                     "plans-b", "fleet-b", "journal-b")
    }
    backend_args = ["--config-backend", "etcd",
                    "--etcd-urls", f"127.0.0.1:{kv.port}"]
    env = {"BALLISTA_FAULTS": f"task.run:-1:delay={task_delay_ms}"}
    port_a, port_b = free_port(), free_port()
    expected = _expected_fingerprints(n_jobs)

    # executor timeout 5s everywhere: the backup's reaper sweeps every
    # 5s and declares a peer scheduler dead after 3 missed sweeps
    # (15s) — while each scheduler's own 1.5s-heartbeat fleet stays
    # comfortably alive
    sched_a = SchedulerProc(
        port_a, free_port(),
        args=_scheduler_args(
            backend_args, dirs["plans-a"], dirs["fleet-a"],
            dirs["journal-a"], executor_timeout_s=5,
        ),
        env=env, log_path=os.path.join(root, "scheduler-a.log"),
    )
    sched_b: Optional[SchedulerProc] = None
    try:
        sched_a.wait_ready()
        sched_b = SchedulerProc(
            port_b, free_port(),
            args=_scheduler_args(
                backend_args, dirs["plans-b"], dirs["fleet-b"],
                dirs["journal-b"], executor_timeout_s=5,
            ),
            env=env, log_path=os.path.join(root, "scheduler-b.log"),
        )
        sched_b.wait_ready()
        # both fleets registered (shared backend: each REST view sees 4)
        sched_a.wait_alive_executors(4)

        ctx = BallistaContext.remote(
            "127.0.0.1", port_a, BallistaConfig(dict(BASE_CONFIG)),
            endpoints=[f"127.0.0.1:{port_b}"],
        )
        ctx.register_table("t", MemoryTable.from_table(_table(), 2))
        job_ids = _submit_burst(ctx, n_jobs)
        threads, results = _start_waiters(ctx, job_ids, job_timeout_s)

        _wait_journal(dirs["journal-a"], "job_queued", n_jobs)
        _wait_journal(dirs["journal-a"], "job_admitted", 1)
        time.sleep(rng.uniform(0.3, 0.9))
        t_kill = sched_a.kill()

        for th in threads:
            th.join(timeout=job_timeout_s + 30)
        _fetch_outputs(ctx, job_ids, results)

        record = _audit_leg(
            "takeover", job_ids, results, expected,
            dirs["journal-a"], dirs["journal-b"], t_kill,
        )
        state = sched_b.rest_get("/api/state")
        record["backup_alive_executors"] = sum(
            1 for e in state["executors"] if e["alive"]
        )
        ctx.close()
        return record
    finally:
        for s in (sched_b, sched_a):
            if s is not None:
                try:
                    s.stop()
                except Exception:  # noqa: BLE001 - cleanup
                    pass
        kill_orphans(dirs["fleet-a"])
        kill_orphans(dirs["fleet-b"])
        kv.stop()


# ------------------------------------------------------------- entry points
def run_chaos_smoke() -> dict:
    """Tier-1 gate (``dev/tier1.sh --chaos-smoke``): the restart leg at
    small scale — full kill/restart mechanics, minutes not tens of."""
    return run_restart_leg(n_jobs=5, task_delay_ms=150, job_timeout_s=180.0)


def run_chaos_bench(n_jobs: int = 10, task_delay_ms: int = 200) -> List[dict]:
    """Both BENCH_SUITE legs, as JSON-Lines-ready records."""
    restart = run_restart_leg(n_jobs=n_jobs, task_delay_ms=task_delay_ms)
    takeover = run_takeover_leg(n_jobs=n_jobs, task_delay_ms=task_delay_ms)
    records = []
    for leg in (restart, takeover):
        records.append(
            {
                "metric": f"scheduler_chaos_{leg['leg']}_mttr_s",
                "value": leg["mttr_first_dispatch_s"],
                "unit": "s (SIGKILL -> first post-recovery admission dispatch)",
                **leg,
            }
        )
    return records


def main() -> None:
    records = run_chaos_bench()
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_SUITE_r20.json")
    with open(out, "w", encoding="utf-8") as f:
        for rec in records:
            line = json.dumps(rec)
            print(line)
            f.write(line + "\n")
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
