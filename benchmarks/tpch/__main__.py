"""TPC-H benchmark harness: ``python -m benchmarks.tpch <subcommand>``.

Counterpart of the reference's ``benchmarks/src/bin/tpch.rs``:

* ``benchmark ballista|local`` — run queries 1-22 for N iterations and
  print a JSON summary with system info (`:69-113`, `:275-330`)
* ``data`` — generate the synthetic dataset as parquet/csv (stands in for
  dbgen; the reference assumes pre-generated .tbl files)
* ``convert`` — convert dbgen ``.tbl`` files to csv/parquet (`:245-249`
  convert subcommand)
* ``loadtest`` — concurrent query storm against a running cluster
  (`:249` loadtest subcommand)

Examples:
    python -m benchmarks.tpch data --path /tmp/tpch --sf 0.1
    python -m benchmarks.tpch benchmark local --path /tmp/tpch --query 6
    python -m benchmarks.tpch benchmark ballista --host localhost --port 50050 \
        --path /tmp/tpch --iterations 3
    python -m benchmarks.tpch convert --input /tmp/tbl --output /tmp/parquet \
        --format parquet
    python -m benchmarks.tpch loadtest --host localhost --port 50050 \
        --path /tmp/tpch --concurrency 4 --num-queries 16
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.parquet as pq

from benchmarks.tpch.datagen import ALL_TABLES, gen_table
from benchmarks.tpch.queries import QUERIES

# dbgen .tbl column schemas (pipe-delimited, trailing delimiter)
TBL_SCHEMAS: dict[str, list[tuple[str, pa.DataType]]] = {
    "lineitem": [
        ("l_orderkey", pa.int64()), ("l_partkey", pa.int64()),
        ("l_suppkey", pa.int64()), ("l_linenumber", pa.int32()),
        ("l_quantity", pa.float64()), ("l_extendedprice", pa.float64()),
        ("l_discount", pa.float64()), ("l_tax", pa.float64()),
        ("l_returnflag", pa.string()), ("l_linestatus", pa.string()),
        ("l_shipdate", pa.date32()), ("l_commitdate", pa.date32()),
        ("l_receiptdate", pa.date32()), ("l_shipinstruct", pa.string()),
        ("l_shipmode", pa.string()), ("l_comment", pa.string()),
    ],
    "orders": [
        ("o_orderkey", pa.int64()), ("o_custkey", pa.int64()),
        ("o_orderstatus", pa.string()), ("o_totalprice", pa.float64()),
        ("o_orderdate", pa.date32()), ("o_orderpriority", pa.string()),
        ("o_clerk", pa.string()), ("o_shippriority", pa.int32()),
        ("o_comment", pa.string()),
    ],
    "customer": [
        ("c_custkey", pa.int64()), ("c_name", pa.string()),
        ("c_address", pa.string()), ("c_nationkey", pa.int64()),
        ("c_phone", pa.string()), ("c_acctbal", pa.float64()),
        ("c_mktsegment", pa.string()), ("c_comment", pa.string()),
    ],
    "part": [
        ("p_partkey", pa.int64()), ("p_name", pa.string()),
        ("p_mfgr", pa.string()), ("p_brand", pa.string()),
        ("p_type", pa.string()), ("p_size", pa.int32()),
        ("p_container", pa.string()), ("p_retailprice", pa.float64()),
        ("p_comment", pa.string()),
    ],
    "supplier": [
        ("s_suppkey", pa.int64()), ("s_name", pa.string()),
        ("s_address", pa.string()), ("s_nationkey", pa.int64()),
        ("s_phone", pa.string()), ("s_acctbal", pa.float64()),
        ("s_comment", pa.string()),
    ],
    "partsupp": [
        ("ps_partkey", pa.int64()), ("ps_suppkey", pa.int64()),
        ("ps_availqty", pa.int32()), ("ps_supplycost", pa.float64()),
        ("ps_comment", pa.string()),
    ],
    "nation": [
        ("n_nationkey", pa.int64()), ("n_name", pa.string()),
        ("n_regionkey", pa.int64()), ("n_comment", pa.string()),
    ],
    "region": [
        ("r_regionkey", pa.int64()), ("r_name", pa.string()),
        ("r_comment", pa.string()),
    ],
}


def _register_tables(ctx, path: str) -> None:
    """Register the 8 tables from a data dir (parquet dirs or csv files)."""
    for name in ALL_TABLES:
        pdir = os.path.join(path, name)
        csv = os.path.join(path, f"{name}.csv")
        if os.path.isdir(pdir):
            ctx.register_parquet(name, pdir)
        elif os.path.exists(csv):
            ctx.register_csv(name, csv)
        else:
            raise SystemExit(f"no data for table {name!r} under {path}")


def _make_context(args):
    if getattr(args, "host", None):
        from arrow_ballista_tpu import BallistaConfig
        from arrow_ballista_tpu.client.context import BallistaContext

        cfg = BallistaConfig(
            {
                "ballista.shuffle.partitions": str(args.partitions),
                "ballista.batch.size": str(args.batch_size),
                # session settings ship with every query, so the executors
                # honor --tpu in cluster mode too
                "ballista.tpu.enable": "true" if args.tpu else "false",
            }
        )
        return BallistaContext.remote(args.host, args.port, cfg)
    from arrow_ballista_tpu import BallistaConfig, SessionContext

    cfg = BallistaConfig(
        {
            "ballista.shuffle.partitions": str(args.partitions),
            "ballista.batch.size": str(args.batch_size),
            "ballista.tpu.enable": "true" if args.tpu else "false",
        }
    )
    return SessionContext(cfg)


def cmd_benchmark(args) -> None:
    ctx = _make_context(args)
    _register_tables(ctx, args.path)
    queries = [args.query] if args.query else sorted(QUERIES)
    results = {}
    for qn in queries:
        times = []
        rows = 0
        for i in range(args.iterations):
            t0 = time.perf_counter()
            out = ctx.sql(QUERIES[qn]).collect()
            dt = (time.perf_counter() - t0) * 1000.0
            rows = out.num_rows
            times.append(dt)
            if args.debug:
                print(f"q{qn} iter {i}: {dt:.1f} ms, {rows} rows", file=sys.stderr)
        results[f"q{qn}"] = {
            "iterations": args.iterations,
            "min_ms": round(min(times), 3),
            "max_ms": round(max(times), 3),
            "avg_ms": round(sum(times) / len(times), 3),
            "rows": rows,
        }
    # summary in the shape of the reference's BenchmarkRun JSON (tpch.rs
    # summary: engine/version/system info + per-query timings)
    summary = {
        "engine": "ballista-tpu" if getattr(args, "host", None) else "local",
        "benchmark_version": "0.7.0-tpu",
        "python_version": platform.python_version(),
        "system": {
            "machine": platform.machine(),
            "processor": platform.processor(),
            "platform": platform.platform(),
        },
        "data_path": args.path,
        "queries": results,
    }
    print(json.dumps(summary, indent=2 if args.debug else None))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(summary, f, indent=2)


def cmd_data(args) -> None:
    os.makedirs(args.path, exist_ok=True)
    for name in ALL_TABLES:
        tbl = gen_table(name, args.sf)
        if args.format == "parquet":
            tdir = os.path.join(args.path, name)
            os.makedirs(tdir, exist_ok=True)
            n = args.partitions if name not in ("nation", "region") else 1
            per = (tbl.num_rows + n - 1) // n
            for i in range(n):
                pq.write_table(
                    tbl.slice(i * per, per),
                    os.path.join(tdir, f"part-{i}.parquet"),
                    compression=args.compression,
                )
        else:
            pacsv.write_csv(tbl, os.path.join(args.path, f"{name}.csv"))
        print(f"wrote {name}: {tbl.num_rows} rows", file=sys.stderr)


def cmd_convert(args) -> None:
    """dbgen .tbl → csv/parquet (reference: tpch.rs convert subcommand)."""
    os.makedirs(args.output, exist_ok=True)
    tables = [args.table] if args.table else ALL_TABLES
    for name in tables:
        tbl_path = os.path.join(args.input, f"{name}.tbl")
        if not os.path.exists(tbl_path):
            print(f"skipping {name}: {tbl_path} not found", file=sys.stderr)
            continue
        schema_cols = TBL_SCHEMAS[name]
        # dbgen emits a trailing '|' per row → one phantom column
        names = [c for c, _ in schema_cols] + ["__trailing"]
        table = pacsv.read_csv(
            tbl_path,
            read_options=pacsv.ReadOptions(column_names=names),
            parse_options=pacsv.ParseOptions(delimiter="|"),
            convert_options=pacsv.ConvertOptions(
                column_types={c: t for c, t in schema_cols},
                include_columns=[c for c, _ in schema_cols],
            ),
        )
        if args.format == "parquet":
            tdir = os.path.join(args.output, name)
            os.makedirs(tdir, exist_ok=True)
            pq.write_table(
                table,
                os.path.join(tdir, "part-0.parquet"),
                compression=args.compression,
            )
        else:
            pacsv.write_csv(table, os.path.join(args.output, f"{name}.csv"))
        print(f"converted {name}: {table.num_rows} rows", file=sys.stderr)


def cmd_loadtest(args) -> None:
    """Concurrent query storm (reference: tpch.rs loadtest subcommand)."""
    import threading

    queries = (
        [args.query] if args.query else sorted(set(QUERIES) & {1, 3, 5, 6, 10, 12})
    )
    errors: list[str] = []
    latencies: list[float] = []
    lock = threading.Lock()

    # distribute num_queries over workers exactly (remainder to the first)
    per_worker = [
        args.num_queries // args.concurrency
        + (1 if i < args.num_queries % args.concurrency else 0)
        for i in range(args.concurrency)
    ]

    def worker(wid: int) -> None:
        ctx = _make_context(args)
        _register_tables(ctx, args.path)
        import random

        rng = random.Random(wid)
        for _ in range(per_worker[wid]):
            qn = rng.choice(queries)
            t0 = time.perf_counter()
            try:
                ctx.sql(QUERIES[qn]).collect()
                with lock:
                    latencies.append((time.perf_counter() - t0) * 1000.0)
            except Exception as e:
                with lock:
                    errors.append(f"q{qn}: {e}")

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(args.concurrency)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    latencies.sort()
    n = len(latencies)
    print(
        json.dumps(
            {
                "completed": n,
                "errors": len(errors),
                "wall_seconds": round(wall, 2),
                "qps": round(n / wall, 2) if wall else 0,
                "p50_ms": round(latencies[n // 2], 1) if n else None,
                "p95_ms": round(latencies[int(n * 0.95)], 1) if n else None,
                "error_samples": errors[:3],
            }
        )
    )
    if errors:
        sys.exit(1)


def main(argv=None) -> None:
    from arrow_ballista_tpu.utils import apply_jax_platform_env

    apply_jax_platform_env()
    ap = argparse.ArgumentParser("tpch", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    b = sub.add_parser("benchmark", help="run TPC-H queries, print JSON summary")
    b.add_argument("mode", choices=["ballista", "local"], help="cluster or in-proc")
    b.add_argument("--host", default=None)
    b.add_argument("--port", type=int, default=50050)
    b.add_argument("--path", required=True, help="data directory")
    b.add_argument("--query", type=int, default=None, choices=sorted(QUERIES))
    b.add_argument("--iterations", type=int, default=3)
    b.add_argument("--partitions", type=int, default=2)
    b.add_argument("--batch-size", type=int, default=8192)
    b.add_argument("--tpu", action="store_true", help="enable the TPU stage compiler")
    b.add_argument("--debug", action="store_true")
    b.add_argument("--output", default=None, help="also write summary JSON here")

    d = sub.add_parser("data", help="generate the synthetic dataset (dbgen stand-in)")
    d.add_argument("--path", required=True)
    d.add_argument("--sf", type=float, default=0.1)
    d.add_argument("--partitions", type=int, default=2)
    d.add_argument("--format", choices=["parquet", "csv"], default="parquet")
    d.add_argument("--compression", default="snappy")

    c = sub.add_parser("convert", help="convert dbgen .tbl files")
    c.add_argument("--input", required=True)
    c.add_argument("--output", required=True)
    c.add_argument("--format", choices=["parquet", "csv"], default="parquet")
    c.add_argument("--compression", default="snappy")
    c.add_argument("--table", default=None, choices=ALL_TABLES)

    lt = sub.add_parser("loadtest", help="concurrent query storm")
    lt.add_argument("--host", default=None)
    lt.add_argument("--port", type=int, default=50050)
    lt.add_argument("--path", required=True)
    lt.add_argument("--query", type=int, default=None, choices=sorted(QUERIES))
    lt.add_argument("--concurrency", type=int, default=4)
    lt.add_argument("--num-queries", type=int, default=16)
    lt.add_argument("--partitions", type=int, default=2)
    lt.add_argument("--batch-size", type=int, default=8192)
    lt.add_argument("--tpu", action="store_true")

    args = ap.parse_args(argv)
    if args.cmd == "benchmark":
        if args.mode == "ballista" and not args.host:
            args.host = "localhost"
        if args.mode == "local":
            args.host = None
        cmd_benchmark(args)
    elif args.cmd == "data":
        cmd_data(args)
    elif args.cmd == "convert":
        cmd_convert(args)
    elif args.cmd == "loadtest":
        cmd_loadtest(args)


if __name__ == "__main__":
    main()
