"""Synthetic TPC-H data generator (numpy, deterministic).

Counterpart of the reference's tbl-file converter workflow
(``benchmarks/src/bin/tpch.rs`` `convert` subcommand): since dbgen isn't
available in this image, tables are generated directly with dbgen-like
distributions — correct schemas, key relationships (orderkey/custkey/
partkey/suppkey joins work), realistic value ranges.  Queries are verified
by cross-checking execution paths (CPU vs TPU vs distributed), not against
official dbgen answers.
"""

from __future__ import annotations

import datetime as dt

import numpy as np
import pyarrow as pa

_EPOCH = dt.date(1970, 1, 1)
_START = (dt.date(1992, 1, 1) - _EPOCH).days
_END = (dt.date(1998, 8, 2) - _EPOCH).days

RETURN_FLAGS = np.array(["A", "N", "R"])
LINE_STATUS = np.array(["F", "O"])
SHIP_MODES = np.array(["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"])
SHIP_INSTRUCT = np.array(
    ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]
)
ORDER_STATUS = np.array(["F", "O", "P"])
PRIORITIES = np.array(["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"])
SEGMENTS = np.array(["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"])
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1), ("EGYPT", 4),
    ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3), ("INDIA", 2), ("INDONESIA", 2),
    ("IRAN", 4), ("IRAQ", 4), ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0),
    ("MOROCCO", 0), ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3), ("UNITED KINGDOM", 3),
    ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
# TPC-H spec P_NAME words (dbgen's colors list, subset) — q9 filters
# '%green%' and q20 'forest%', so part names must draw from these
P_NAME_WORDS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
    "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
    "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
    "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
    "hot", "indian", "ivory", "khaki", "lace", "lavender", "lawn", "lemon",
    "light", "lime", "linen", "magenta", "maroon", "medium", "metallic",
    "midnight", "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange",
    "orchid", "pale", "papaya", "peach", "peru", "pink", "plum", "powder",
    "puff", "purple", "red", "rose", "rosy", "royal", "saddle", "salmon",
    "sandy", "seashell", "sienna", "sky", "slate", "smoke", "snow", "spring",
    "steel", "tan", "thistle", "tomato", "turquoise", "violet", "wheat", "white",
    "yellow",
]

PART_TYPES_1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
PART_TYPES_2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
PART_TYPES_3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINERS_1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINERS_2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]


def _dates(rng: np.random.Generator, n: int) -> np.ndarray:
    return rng.integers(_START, _END, n, dtype=np.int32)


def gen_lineitem(sf: float, seed: int = 42) -> pa.Table:
    rng = np.random.default_rng(seed)
    n_orders = int(1_500_000 * sf)
    lines_per_order = rng.integers(1, 8, n_orders)
    n = int(lines_per_order.sum())
    orderkey = np.repeat(_orderkeys(n_orders), lines_per_order)
    # vectorized within-order line numbers (a 15M-iteration Python loop at
    # SF10 otherwise dominates datagen)
    starts = np.cumsum(lines_per_order) - lines_per_order
    linenumber = (
        np.arange(n, dtype=np.int64) - np.repeat(starts, lines_per_order) + 1
    ).astype(np.int32)
    quantity = rng.integers(1, 51, n).astype(np.float64)
    extendedprice = np.round(rng.uniform(900.0, 105000.0, n), 2)
    discount = np.round(rng.integers(0, 11, n) / 100.0, 2)
    tax = np.round(rng.integers(0, 9, n) / 100.0, 2)
    shipdate = _dates(rng, n)
    commitdate = shipdate + rng.integers(-30, 60, n)
    receiptdate = shipdate + rng.integers(1, 31, n)
    rf = np.where(
        receiptdate <= (dt.date(1995, 6, 17) - _EPOCH).days,
        rng.choice(np.array(["A", "R"]), n),
        "N",
    )
    ls = np.where(shipdate > (dt.date(1995, 6, 17) - _EPOCH).days, "O", "F")
    return pa.table(
        {
            "l_orderkey": pa.array(orderkey, pa.int64()),
            "l_partkey": pa.array(rng.integers(1, max(int(200_000 * sf), 2), n), pa.int64()),
            "l_suppkey": pa.array(rng.integers(1, max(int(10_000 * sf), 2), n), pa.int64()),
            "l_linenumber": pa.array(linenumber, pa.int32()),
            "l_quantity": pa.array(quantity, pa.float64()),
            "l_extendedprice": pa.array(extendedprice, pa.float64()),
            "l_discount": pa.array(discount, pa.float64()),
            "l_tax": pa.array(tax, pa.float64()),
            "l_returnflag": pa.array(rf, pa.string()),
            "l_linestatus": pa.array(ls, pa.string()),
            "l_shipdate": pa.array(shipdate, pa.date32()),
            "l_commitdate": pa.array(commitdate.astype(np.int32), pa.date32()),
            "l_receiptdate": pa.array(receiptdate.astype(np.int32), pa.date32()),
            "l_shipinstruct": pa.array(rng.choice(SHIP_INSTRUCT, n), pa.string()),
            "l_shipmode": pa.array(rng.choice(SHIP_MODES, n), pa.string()),
            "l_comment": pa.array(_comments(rng, n), pa.string()),
        }
    )


def _orderkeys(n_orders: int) -> np.ndarray:
    # dbgen sparsifies order keys: 8 per 32-key block
    blocks = np.arange(n_orders) // 8
    within = np.arange(n_orders) % 8
    return (blocks * 32 + within + 1).astype(np.int64)


def _comments(rng: np.random.Generator, n: int) -> np.ndarray:
    words = np.array(
        ["furiously", "quickly", "special", "pending", "final", "express",
         "regular", "ironic", "even", "bold", "silent", "deposits", "accounts",
         "requests", "packages", "theodolites", "instructions", "foxes"]
    )
    return np.char.add(
        np.char.add(rng.choice(words, n), " "), rng.choice(words, n)
    )


def _part_names(rng: np.random.Generator, n: int) -> np.ndarray:
    # dbgen: P_NAME is 5 distinct color words; 2 suffice for the LIKE
    # predicates ('forest%' prefix, '%green%' containment) to hit
    w = np.array(P_NAME_WORDS)
    return np.char.add(
        np.char.add(rng.choice(w, n), " "), rng.choice(w, n)
    )


def gen_orders(sf: float, seed: int = 43) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = int(1_500_000 * sf)
    orderkey = _orderkeys(n)
    orderdate = _dates(rng, n)
    return pa.table(
        {
            "o_orderkey": pa.array(orderkey, pa.int64()),
            "o_custkey": pa.array(rng.integers(1, max(int(150_000 * sf), 2), n), pa.int64()),
            "o_orderstatus": pa.array(rng.choice(ORDER_STATUS, n), pa.string()),
            "o_totalprice": pa.array(np.round(rng.uniform(850.0, 600000.0, n), 2), pa.float64()),
            "o_orderdate": pa.array(orderdate, pa.date32()),
            "o_orderpriority": pa.array(rng.choice(PRIORITIES, n), pa.string()),
            "o_clerk": pa.array(
                np.char.add("Clerk#", rng.integers(1, 1001, n).astype(str)), pa.string()
            ),
            "o_shippriority": pa.array(np.zeros(n, np.int32), pa.int32()),
            "o_comment": pa.array(_comments(rng, n), pa.string()),
        }
    )


def gen_customer(sf: float, seed: int = 44) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = int(150_000 * sf)
    key = np.arange(1, n + 1, dtype=np.int64)
    return pa.table(
        {
            "c_custkey": pa.array(key, pa.int64()),
            "c_name": pa.array(np.char.add("Customer#", key.astype(str)), pa.string()),
            "c_address": pa.array(_comments(rng, n), pa.string()),
            "c_nationkey": pa.array(rng.integers(0, 25, n), pa.int64()),
            "c_phone": pa.array(
                np.char.add(rng.integers(10, 35, n).astype(str),
                            np.char.add("-", rng.integers(100, 1000, n).astype(str))),
                pa.string(),
            ),
            "c_acctbal": pa.array(np.round(rng.uniform(-999.99, 9999.99, n), 2), pa.float64()),
            "c_mktsegment": pa.array(rng.choice(SEGMENTS, n), pa.string()),
            "c_comment": pa.array(_comments(rng, n), pa.string()),
        }
    )


def gen_part(sf: float, seed: int = 45) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = int(200_000 * sf)
    key = np.arange(1, n + 1, dtype=np.int64)
    ptype = np.char.add(
        np.char.add(rng.choice(np.array(PART_TYPES_1), n), " "),
        np.char.add(
            np.char.add(rng.choice(np.array(PART_TYPES_2), n), " "),
            rng.choice(np.array(PART_TYPES_3), n),
        ),
    )
    container = np.char.add(
        np.char.add(rng.choice(np.array(CONTAINERS_1), n), " "),
        rng.choice(np.array(CONTAINERS_2), n),
    )
    return pa.table(
        {
            "p_partkey": pa.array(key, pa.int64()),
            "p_name": pa.array(_part_names(rng, n), pa.string()),
            "p_mfgr": pa.array(
                np.char.add("Manufacturer#", rng.integers(1, 6, n).astype(str)),
                pa.string(),
            ),
            "p_brand": pa.array(
                np.char.add("Brand#", rng.integers(11, 56, n).astype(str)), pa.string()
            ),
            "p_type": pa.array(ptype, pa.string()),
            "p_size": pa.array(rng.integers(1, 51, n).astype(np.int32), pa.int32()),
            "p_container": pa.array(container, pa.string()),
            "p_retailprice": pa.array(np.round(900 + key % 1000 + 0.01 * (key % 100), 2), pa.float64()),
            "p_comment": pa.array(_comments(rng, n), pa.string()),
        }
    )


def gen_supplier(sf: float, seed: int = 46) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = int(10_000 * sf)
    key = np.arange(1, n + 1, dtype=np.int64)
    return pa.table(
        {
            "s_suppkey": pa.array(key, pa.int64()),
            "s_name": pa.array(np.char.add("Supplier#", key.astype(str)), pa.string()),
            "s_address": pa.array(_comments(rng, n), pa.string()),
            "s_nationkey": pa.array(rng.integers(0, 25, n), pa.int64()),
            "s_phone": pa.array(
                np.char.add(rng.integers(10, 35, n).astype(str),
                            np.char.add("-", rng.integers(100, 1000, n).astype(str))),
                pa.string(),
            ),
            "s_acctbal": pa.array(np.round(rng.uniform(-999.99, 9999.99, n), 2), pa.float64()),
            "s_comment": pa.array(_comments(rng, n), pa.string()),
        }
    )


def gen_partsupp(sf: float, seed: int = 47) -> pa.Table:
    rng = np.random.default_rng(seed)
    n_part = int(200_000 * sf)
    partkey = np.repeat(np.arange(1, n_part + 1, dtype=np.int64), 4)
    n = len(partkey)
    suppkey = rng.integers(1, max(int(10_000 * sf), 2), n)
    return pa.table(
        {
            "ps_partkey": pa.array(partkey, pa.int64()),
            "ps_suppkey": pa.array(suppkey, pa.int64()),
            "ps_availqty": pa.array(rng.integers(1, 10000, n).astype(np.int32), pa.int32()),
            "ps_supplycost": pa.array(np.round(rng.uniform(1.0, 1000.0, n), 2), pa.float64()),
            "ps_comment": pa.array(_comments(rng, n), pa.string()),
        }
    )


def gen_nation() -> pa.Table:
    return pa.table(
        {
            "n_nationkey": pa.array(np.arange(25, dtype=np.int64), pa.int64()),
            "n_name": pa.array([n for n, _ in NATIONS], pa.string()),
            "n_regionkey": pa.array([r for _, r in NATIONS], pa.int64()),
            "n_comment": pa.array(["" for _ in NATIONS], pa.string()),
        }
    )


def gen_region() -> pa.Table:
    return pa.table(
        {
            "r_regionkey": pa.array(np.arange(5, dtype=np.int64), pa.int64()),
            "r_name": pa.array(REGIONS, pa.string()),
            "r_comment": pa.array(["" for _ in REGIONS], pa.string()),
        }
    )


GENERATORS = {
    "lineitem": gen_lineitem,
    "orders": gen_orders,
    "customer": gen_customer,
    "part": gen_part,
    "supplier": gen_supplier,
    "partsupp": gen_partsupp,
}


def gen_table(name: str, sf: float) -> pa.Table:
    if name == "nation":
        return gen_nation()
    if name == "region":
        return gen_region()
    return GENERATORS[name](sf)


ALL_TABLES = ["lineitem", "orders", "customer", "part", "supplier", "partsupp", "nation", "region"]


def register_all(ctx, sf: float = 0.01, partitions: int = 1) -> None:
    """Register all 8 TPC-H tables as in-memory tables on a context."""
    from arrow_ballista_tpu.catalog import MemoryTable

    for name in ALL_TABLES:
        tbl = gen_table(name, sf)
        ctx.register_table(name, MemoryTable.from_table(tbl, partitions))


def write_parquet(dir_path: str, sf: float = 0.1, partitions: int = 2) -> None:
    """Materialize the dataset as partitioned parquet files."""
    import os

    import pyarrow.parquet as pq

    for name in ALL_TABLES:
        tbl = gen_table(name, sf)
        tdir = os.path.join(dir_path, name)
        os.makedirs(tdir, exist_ok=True)
        n = partitions if name not in ("nation", "region") else 1
        rows = tbl.num_rows
        per = (rows + n - 1) // n
        for i in range(n):
            pq.write_table(tbl.slice(i * per, per), os.path.join(tdir, f"part-{i}.parquet"))
