"""Shuffle data-plane locality A/B micro-benchmark.

Same-host zero-copy vs forced-remote Flight on IDENTICAL inputs (ISSUE
10 acceptance): the local leg serves partitions via ``pa.memory_map``
through the executor-identity transport decision, the remote legs force
``ballista.shuffle.local_transport=off`` so every byte pays the
gRPC/Flight loopback — once per-partition (the old data plane) and once
through the batched multi-partition DoGet.  All three legs must produce
the same sha256 row fingerprint; the local leg's throughput is the
``shuffle_local_fetch_mb_per_sec`` metric (target: ≥ 2x the
Flight-loopback leg) and the batched leg must pay fewer round trips at
no MB/s regression.

Reported by ``bench_suite.py shuffle``; ``run_locality_smoke`` runs on
tiny inputs from ``dev/tier1.sh --bench-smoke``.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import time

import numpy as np
import pyarrow as pa


def _make_partition_files(
    work_dir: str, n_locations: int, mb_per_location: float, batch_rows: int
):
    """One IPC file per map-side location under the canonical
    work_dir/<job>/<stage>/<out>/ layout (the Flight server only serves
    paths inside its work dir)."""
    rng = np.random.default_rng(23)
    schema = pa.schema(
        [
            pa.field("k", pa.int64()),
            pa.field("a", pa.float64()),
            pa.field("b", pa.float64()),
        ]
    )
    bytes_per_row = 24
    rows = max(batch_rows, int(mb_per_location * (1 << 20)) // bytes_per_row)
    paths = []
    total_bytes = 0
    for i in range(n_locations):
        path = os.path.join(work_dir, "benchjob", "1", str(i), "data-0.arrow")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with pa.OSFile(path, "wb") as f:
            with pa.ipc.new_file(f, schema) as w:
                for lo in range(0, rows, batch_rows):
                    n = min(batch_rows, rows - lo)
                    w.write_batch(
                        pa.record_batch(
                            {
                                "k": pa.array(
                                    rng.integers(0, 1 << 30, n), pa.int64()
                                ),
                                "a": pa.array(rng.normal(size=n)),
                                "b": pa.array(rng.normal(size=n)),
                            },
                            schema=schema,
                        )
                    )
        total_bytes += os.path.getsize(path)
        paths.append(path)
    return schema, paths, total_bytes


def _locations(paths, meta):
    from arrow_ballista_tpu.serde.scheduler_types import (
        PartitionId,
        PartitionLocation,
        PartitionStats,
    )

    return [
        PartitionLocation(
            PartitionId("benchjob", 1, i),
            meta,
            PartitionStats(1, 1, 1),
            p,
        )
        for i, p in enumerate(paths)
    ]


def _fingerprint(batches) -> tuple[str, int, int]:
    """(sha256 over the SORTED rows, n_rows, n_bytes): an order-
    insensitive bit-identity check — the legs deliver the same multiset
    in different arrival orders.  numpy lexsort, not pyarrow sort."""
    ks, as_, bs = [], [], []
    nbytes = 0
    for b in batches:
        nbytes += b.nbytes
        ks.append(np.asarray(b.column(0)))
        as_.append(np.asarray(b.column(1)))
        bs.append(np.asarray(b.column(2)))
    k = np.concatenate(ks) if ks else np.array([], np.int64)
    a = np.concatenate(as_) if as_ else np.array([], np.float64)
    bb = np.concatenate(bs) if bs else np.array([], np.float64)
    order = np.lexsort((bb.view(np.int64), a.view(np.int64), k))
    h = hashlib.sha256()
    h.update(k[order].tobytes())
    h.update(a[order].tobytes())
    h.update(bb[order].tobytes())
    return h.hexdigest(), int(k.size), nbytes


def run_locality_bench(
    n_locations: int = 16,
    mb_per_location: float = 4.0,
    batch_rows: int = 65536,
    concurrency: int = 8,
    work_dir: str | None = None,
    iters: int = 3,
) -> dict:
    from arrow_ballista_tpu.config import BallistaConfig
    from arrow_ballista_tpu.exec.operators import TaskContext
    from arrow_ballista_tpu.flight.server import FlightServerHandle
    from arrow_ballista_tpu.serde.scheduler_types import ExecutorMetadata
    from arrow_ballista_tpu.shuffle import ShuffleReaderExec, transport

    own_dir = None
    if work_dir is None:
        own_dir = tempfile.TemporaryDirectory(prefix="shuffle-locality-")
        work_dir = own_dir.name
    server = None
    try:
        schema, paths, total_bytes = _make_partition_files(
            work_dir, n_locations, mb_per_location, batch_rows
        )
        server = FlightServerHandle(work_dir, "127.0.0.1", 0).start()
        meta = ExecutorMetadata("bench-exec", "127.0.0.1", server.port)
        locs = _locations(paths, meta)
        # the deliberate identity decision, not the probe fallback: this
        # process "hosts" an executor on the serving host
        transport.register_local_executor("bench-local", "127.0.0.1")

        def run(settings: dict):
            reader = ShuffleReaderExec(1, schema, [locs])
            ctx = TaskContext(
                config=BallistaConfig(
                    {
                        "ballista.shuffle.fetch_concurrency": str(concurrency),
                        **settings,
                    }
                )
            )
            # time ONLY the fetch; the identity fingerprint (concat +
            # lexsort + sha over 64MB) is leg-invariant and would wash
            # out the transport difference if it sat inside the window
            t0 = time.perf_counter()
            batches = list(reader.execute(0, ctx))
            elapsed = time.perf_counter() - t0
            fp = _fingerprint(batches)
            vals = reader.metrics.to_dict()
            return elapsed, fp, vals

        remote = {"ballista.shuffle.local_transport": "off"}
        unbatched = {**remote, "ballista.shuffle.fetch_batched": "false"}
        run({})  # warm the page cache so every leg reads warm files

        def best_of(settings: dict):
            # best-of-iters: loopback legs are load-noisy on a
            # cpu-shares-limited box; the minimum is the honest capability
            out = None
            for _ in range(max(1, iters)):
                r = run(settings)
                if out is None or r[0] < out[0]:
                    out = r
            return out

        local_s, local_fp, local_m = best_of({})
        # the two REMOTE legs interleave (b,u,b,u,...) and report their
        # MEDIANS: both are pure CPU-scheduling-bound over loopback, so
        # back-to-back blocks would hand whichever leg ran during a
        # quieter slice a phantom win
        rb_runs, ru_runs = [], []
        for _ in range(max(1, iters)):
            rb_runs.append(run(remote))
            ru_runs.append(run(unbatched))
        rb_s, rb_fp, rb_m = sorted(rb_runs, key=lambda r: r[0])[
            len(rb_runs) // 2
        ]
        ru_s, ru_fp, ru_m = sorted(ru_runs, key=lambda r: r[0])[
            len(ru_runs) // 2
        ]
        if not (local_fp == rb_fp == ru_fp):
            raise AssertionError(
                f"transport legs disagree: local={local_fp[0][:16]} "
                f"batched={rb_fp[0][:16]} unbatched={ru_fp[0][:16]}"
            )
        assert local_m.get("local_fetches", 0) == n_locations
        assert local_m.get("fetch_round_trips", 0) == 0
        assert rb_m.get("fetch_round_trips", 0) < n_locations
        assert ru_m.get("fetch_round_trips", 0) == n_locations
        total_mb = total_bytes / (1 << 20)
        return {
            "total_mb": round(total_mb, 2),
            "n_locations": n_locations,
            "concurrency": concurrency,
            "rows": local_fp[1],
            "fingerprint": local_fp[0],
            "local_s": round(local_s, 4),
            "remote_batched_s": round(rb_s, 4),
            "remote_unbatched_s": round(ru_s, 4),
            "local_mb_per_sec": round(total_mb / local_s, 2),
            "remote_batched_mb_per_sec": round(total_mb / rb_s, 2),
            "remote_unbatched_mb_per_sec": round(total_mb / ru_s, 2),
            # acceptance: same-host zero-copy ≥ 2x the Flight loopback
            "local_vs_remote": round(ru_s / local_s, 3),
            # batched: fewer round trips, no MB/s regression
            "batched_round_trips": int(rb_m.get("fetch_round_trips", 0)),
            "unbatched_round_trips": int(ru_m.get("fetch_round_trips", 0)),
            "batched_vs_unbatched": round(ru_s / rb_s, 3),
        }
    finally:
        from arrow_ballista_tpu.shuffle import transport as _t

        _t.unregister_local_executor("bench-local")
        if server is not None:
            server.shutdown()
        if own_dir is not None:
            own_dir.cleanup()


def run_locality_smoke() -> dict:
    """Tiny-input compile/identity smoke for dev/tier1.sh --bench-smoke:
    asserts the three legs agree bit-for-bit, the local leg actually
    went zero-copy and the batched leg paid fewer round trips.  NOT a
    measurement."""
    rec = run_locality_bench(
        n_locations=4, mb_per_location=0.25, batch_rows=4096, concurrency=2
    )
    assert rec["rows"] > 0
    assert rec["batched_round_trips"] < rec["unbatched_round_trips"]
    return rec
