"""NYC-taxi benchmark: ``python -m benchmarks.nyctaxi``.

Counterpart of the reference's ``benchmarks/src/bin/nyctaxi.rs``: registers
the yellow-tripdata table and runs the aggregate benchmark query
(min/max fare grouped by passenger count) against either a local context
or a cluster, printing per-iteration timings.  A ``data`` subcommand
generates a synthetic tripdata file in the 2022 yellow-taxi schema subset.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

BENCH_QUERY = """
select
    passenger_count,
    min(fare_amount) as min_fare,
    max(fare_amount) as max_fare,
    avg(fare_amount) as avg_fare,
    sum(total_amount) as total_revenue,
    count(*) as trips
from tripdata
group by passenger_count
order by passenger_count
"""


def gen_tripdata(n_rows: int, seed: int = 7) -> pa.Table:
    rng = np.random.default_rng(seed)
    distance = np.round(rng.gamma(2.0, 1.8, n_rows), 2)
    fare = np.round(2.5 + distance * 2.7 + rng.normal(0, 1.5, n_rows).clip(0), 2)
    tip = np.round(fare * rng.uniform(0, 0.35, n_rows), 2)
    return pa.table(
        {
            "vendor_id": pa.array(rng.integers(1, 3, n_rows).astype(np.int32)),
            "passenger_count": pa.array(
                rng.integers(1, 7, n_rows).astype(np.int32)
            ),
            "trip_distance": pa.array(distance),
            "fare_amount": pa.array(fare),
            "tip_amount": pa.array(tip),
            "total_amount": pa.array(np.round(fare + tip, 2)),
            "payment_type": pa.array(
                rng.choice(np.array(["CSH", "CRD", "DIS", "NOC"]), n_rows)
            ),
        }
    )


def main(argv=None) -> None:
    from arrow_ballista_tpu.utils import apply_jax_platform_env

    apply_jax_platform_env()
    ap = argparse.ArgumentParser("nyctaxi", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    d = sub.add_parser("data", help="generate synthetic tripdata parquet")
    d.add_argument("--path", required=True)
    d.add_argument("--rows", type=int, default=1_000_000)

    b = sub.add_parser("benchmark", help="run the aggregate benchmark")
    b.add_argument("mode", choices=["ballista", "local"])
    b.add_argument("--host", default="localhost")
    b.add_argument("--port", type=int, default=50050)
    b.add_argument("--path", required=True, help="tripdata parquet file/dir")
    b.add_argument("--iterations", type=int, default=3)
    b.add_argument("--partitions", type=int, default=2)
    b.add_argument("--tpu", action="store_true")

    args = ap.parse_args(argv)
    if args.cmd == "data":
        os.makedirs(os.path.dirname(os.path.abspath(args.path)), exist_ok=True)
        tbl = gen_tripdata(args.rows)
        pq.write_table(tbl, args.path)
        print(f"wrote {args.rows} rows to {args.path}", file=sys.stderr)
        return

    if args.mode == "ballista":
        from arrow_ballista_tpu import BallistaConfig
        from arrow_ballista_tpu.client.context import BallistaContext

        ctx = BallistaContext.remote(
            args.host,
            args.port,
            BallistaConfig(
                {
                    "ballista.shuffle.partitions": str(args.partitions),
                    "ballista.tpu.enable": "true" if args.tpu else "false",
                }
            ),
        )
    else:
        from arrow_ballista_tpu import BallistaConfig, SessionContext

        ctx = SessionContext(
            BallistaConfig(
                {
                    "ballista.shuffle.partitions": str(args.partitions),
                    "ballista.tpu.enable": "true" if args.tpu else "false",
                }
            )
        )
    ctx.register_parquet("tripdata", args.path)
    times = []
    rows = 0
    for i in range(args.iterations):
        t0 = time.perf_counter()
        out = ctx.sql(BENCH_QUERY).collect()
        dt = (time.perf_counter() - t0) * 1000.0
        times.append(dt)
        rows = out.num_rows
        print(f"iteration {i}: {dt:.1f} ms ({rows} groups)", file=sys.stderr)
    print(
        json.dumps(
            {
                "benchmark": "nyctaxi",
                "engine": args.mode,
                "min_ms": round(min(times), 2),
                "avg_ms": round(sum(times) / len(times), 2),
                "groups": rows,
            }
        )
    )


if __name__ == "__main__":
    main()
