"""Observability overhead + query-doctor smoke (ISSUE 13).

Two entry points:

* :func:`run_obs_bench` — re-measures the obs planes' cost with the new
  attribution pass in the picture (PR 3 methodology: price the code the
  hot path actually runs against the bench_suite shuffle leg, rather
  than trusting a noisy wall-clock A/B):

  - **disabled path**: the per-call cost of the disabled span API plus
    the scheduler's new per-task timestamp anchors (two ``time.time_ns``
    reads per task), charged at the shuffle leg's call counts;
  - **enabled path**: the full attribution pass (``obs.doctor.
    job_report`` — profile + critical path + doctor) timed over a real
    completed job's detail.  The pass runs ON DEMAND (REST/explain
    requests), never per task, so its cost is reported both absolute and
    relative to the shuffle leg.

  Emits ``obs_overhead_pct`` (acceptance: < 2% of the shuffle leg) with
  a ``breakdown`` field carrying the measured job's category breakdown —
  the trajectory report renders its dominant categories.

* :func:`run_doctor_smoke` — tier-1 ``--bench-smoke`` gate: a tiny
  standalone job whose ``/api/jobs/{id}/critical_path`` must return a
  path whose category sum is within tolerance of wall-clock, and at
  least one doctor finding on a manufactured skewed input.
"""

from __future__ import annotations

import json
import time
import urllib.request

import pyarrow as pa

CLUSTER_CONFIG = {
    "ballista.obs.enabled": "true",
    "ballista.mesh.enable": "false",
    "ballista.shuffle.partitions": "2",
    "ballista.tpu.min_rows": "0",
}


def _run_cluster_job(extra_config=None, straggler_ms: int = 0):
    """One tiny standalone group-by; returns (cp, profile, wall_info)
    read over real HTTP.  ``straggler_ms`` arms a task.run delay fault
    on partition 1 (the manufactured skew input)."""
    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.config import BallistaConfig
    from arrow_ballista_tpu.context import MemoryTable
    from arrow_ballista_tpu.scheduler.api import ApiServerHandle
    from arrow_ballista_tpu.testing import faults

    cfg = dict(CLUSTER_CONFIG)
    cfg.update(extra_config or {})
    ctx = BallistaContext.standalone(
        config=BallistaConfig(cfg), num_executors=2, concurrent_tasks=2
    )
    try:
        ctx.register_table(
            "t",
            MemoryTable.from_table(
                pa.table(
                    {
                        "g": ["a", "b", "c", "d"] * 250,
                        "x": [1.0, 2.0, 3.0, 4.0] * 250,
                    }
                ),
                2,
            ),
        )
        if straggler_ms:
            faults.arm(
                "task.run",
                times=1,
                action="delay",
                delay_ms=straggler_ms,
                match=lambda partition_id=0, speculative=False, **_:
                    partition_id == 1 and not speculative,
            )
        ctx.sql("select g, sum(x) as s from t group by g").collect()
        (job_id,) = ctx._job_ids
        scheduler, _ = ctx._standalone_handles
        scheduler.server.drain()
        detail = scheduler.server.state.task_manager.get_job_detail(job_id)
        api = ApiServerHandle(scheduler.server, "127.0.0.1", 0).start()
        try:
            base = f"http://127.0.0.1:{api.port}"
            cp = json.load(
                urllib.request.urlopen(
                    f"{base}/api/jobs/{job_id}/critical_path"
                )
            )
            prof = json.load(
                urllib.request.urlopen(f"{base}/api/jobs/{job_id}/profile")
            )
        finally:
            api.stop()
        return cp, prof, detail
    finally:
        faults.clear()
        ctx.close()


def _shuffle_leg_ns() -> tuple:
    """The PR 3 pricing denominator: the instrumented fetch path driven
    the way benchmarks/shuffle_fetch.py does, obs off.  Returns
    (leg_ns, n_locations)."""
    from arrow_ballista_tpu.obs import trace
    from arrow_ballista_tpu.shuffle.fetcher import FetchPolicy, ShuffleFetcher

    trace.configure(enabled=False)

    class _Loc:
        path = ""

    class _M:
        def add(self, *a):
            pass

    n_locations, batches_per_loc = 32, 8
    batch = pa.record_batch([pa.array(list(range(256)))], names=["x"])

    def fetch_fn(loc):
        for _ in range(batches_per_loc):
            yield batch

    def run_leg() -> float:
        t0 = time.perf_counter_ns()
        fetcher = ShuffleFetcher(
            [_Loc() for _ in range(n_locations)],
            FetchPolicy(concurrency=8),
            _M(),
            fetch_fn=fetch_fn,
        )
        sum(b.num_rows for b in fetcher)
        return time.perf_counter_ns() - t0

    run_leg()  # warm
    return min(run_leg() for _ in range(3)), n_locations


def run_obs_bench() -> dict:
    from arrow_ballista_tpu.obs import trace
    from arrow_ballista_tpu.obs.doctor import job_report

    leg_ns, n_locations = _shuffle_leg_ns()

    # disabled span API per-call cost (one global read + return NOOP)
    calls = 100_000
    t0 = time.perf_counter_ns()
    for _ in range(calls):
        trace.span("x")
    span_call_ns = (time.perf_counter_ns() - t0) / calls
    # the new timestamp anchors: two wall-clock reads + dict stores per
    # task attempt (dispatch + commit), always on
    t0 = time.perf_counter_ns()
    anchors: dict = {}
    for i in range(calls):
        anchors[i & 63] = time.time_ns()
    anchor_ns = (time.perf_counter_ns() - t0) / calls
    # charge like PR 3: 3 span entries per location + 8, plus 2 anchor
    # writes per location-as-task (a leg task is at most one location)
    disabled_ns = (3 * n_locations + 8) * span_call_ns + (
        2 * n_locations
    ) * anchor_ns
    disabled_pct = 100.0 * disabled_ns / leg_ns

    # enabled path: the full attribution pass over a real completed job
    cp, prof, detail = _run_cluster_job()
    t0 = time.perf_counter_ns()
    iters = 50
    for _ in range(iters):
        job_report(detail, [], [])
    attribution_ms = (time.perf_counter_ns() - t0) / iters / 1e6
    attribution_pct = 100.0 * (attribution_ms * 1e6) / leg_ns

    return {
        "metric": "obs_overhead_pct",
        "value": round(disabled_pct, 4),
        "unit": "% of shuffle leg",
        "disabled_span_call_ns": round(span_call_ns, 1),
        "timestamp_anchor_ns": round(anchor_ns, 1),
        "shuffle_leg_ms": round(leg_ns / 1e6, 3),
        "attribution_pass_ms": round(attribution_ms, 3),
        "attribution_pct_of_shuffle_leg": round(attribution_pct, 3),
        "job_wall_clock_ms": cp.get("wall_clock_ms"),
        "coverage": cp.get("coverage"),
        # the measured job's category breakdown rides the record: the
        # trajectory report (dev/bench_report.py) renders its dominant
        # categories next to the overhead number
        "breakdown": cp.get("breakdown"),
    }


def run_doctor_smoke(tolerance: float = 0.05) -> dict:
    """Tier-1 gate: breakdown sums to wall-clock within ``tolerance``
    and the doctor fires on a manufactured skewed input.  The straggler
    delay must dominate the fast task's runtime INCLUDING its first-run
    XLA compile (~300ms on a slow box), or max/median can land under the
    skew coefficient and the gate flakes."""
    cp, prof, _detail = _run_cluster_job(straggler_ms=1500)
    assert cp.get("complete") is True, f"incomplete attribution: {cp}"
    wall = cp["wall_clock_ms"]
    total = cp["breakdown_total_ms"]
    assert wall > 0 and abs(total - wall) <= tolerance * wall, (
        f"breakdown {total}ms vs wall {wall}ms outside {tolerance:.0%}"
    )
    assert cp["breakdown"]["scheduling_delay_ms"] > 0
    skew = [f for f in cp.get("doctor", []) if f["code"] == "skewed_stage"]
    assert skew, f"manufactured straggler produced no skew finding: {cp['doctor']}"
    stage_ids = {s["stage_id"] for s in prof["stages"]}
    assert skew[0]["stage_id"] in stage_ids
    assert skew[0]["evidence"]["slowest_partition"] == 1
    return {
        "wall_clock_ms": wall,
        "breakdown_total_ms": total,
        "coverage": cp.get("coverage"),
        "findings": [f["code"] for f in cp.get("doctor", [])],
        "skew_stage": skew[0]["stage_id"],
    }
