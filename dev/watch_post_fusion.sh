#!/usr/bin/env bash
# Round-5 post-fix trip-wire: wait for the main capture_chip.sh run to
# drain (one job at a time on this box), then poll the device probe and
# fire capture_post_fusion.sh on first recovery.
#
#   nohup bash dev/watch_post_fusion.sh > dev/watch_post_fusion.log 2>&1 &

set -u
cd "$(dirname "$0")/.."

STATUS=dev/watch_post_fusion.status
INTERVAL="${WATCH_INTERVAL_S:-480}"

while pgrep -f "capture_chip.sh" > /dev/null 2>&1; do
  echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) main capture still running" >> "$STATUS"
  sleep 120
done

probe_once() {
  timeout 200 python -c "
from benchmarks.device_guard import probe_backend
import sys
p = probe_backend(180)
print('probe:', p)
sys.exit(0 if p not in (None, 'timeout', 'cpu') else 1)
"
}

n=0
while true; do
  n=$((n + 1))
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  if out=$(probe_once 2>&1); then
    echo "$ts probe#$n OK — starting post-fusion capture" | tee -a "$STATUS"
    bash dev/capture_post_fusion.sh >> dev/capture_post_fusion.log 2>&1
    rc=$?
    echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) post-fusion capture rc=$rc" | tee -a "$STATUS"
    if [ "$rc" -eq 0 ]; then
      echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) DONE" | tee -a "$STATUS"
      exit 0
    fi
    # failed steps: keep watching so a later window can rerun
  else
    echo "$ts probe#$n unavailable: $out" >> "$STATUS"
  fi
  sleep "$INTERVAL"
done
