#!/usr/bin/env bash
# Post-fusion A/B re-capture (round 5): after the single-dispatch fused
# runner lands, re-measure the headline configs against the pre-fusion
# rows already in BENCH_SUITE_r05.json / BENCH_r05_dev.json.
#
# Appends to BENCH_SUITE_r05.json (bench_suite._emit appends); bench.py
# rewrites BENCH_r05_dev.json via tee.  AB_FUSION_r05.log captures the
# before/after pairing for the README table.

set -u -o pipefail
cd "$(dirname "$0")/.."

# persistent XLA compile cache: each step is a fresh process, and chip
# windows are scarce — don't spend them recompiling identical kernels
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"

fails=0
step() {
  local name="$1" t="$2"
  shift 2
  echo "== $name =="
  timeout "$t" "$@"
  local rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "!! step '$name' failed (rc=$rc)"
    fails=$((fails + 1))
  fi
}

probe() {
  timeout 200 python -c "
from benchmarks.device_guard import probe_backend
import sys
p = probe_backend(180)
print('probe:', p)
sys.exit(0 if p not in (None, 'timeout', 'cpu') else 1)
"
}

echo "== probing device =="
if ! probe; then
  echo "device unavailable — aborting (nothing written)"
  exit 2
fi

{
  echo "== post-fusion capture $(date -u +%Y-%m-%dT%H:%M:%SZ) =="
} | tee -a AB_FUSION_r05.log

step "post-fusion q6" 3600 bash -c \
  'set -o pipefail; python bench_suite.py q6 2>&1 | tail -1 | tee -a AB_FUSION_r05.log'
step "post-fusion bench.py (q1 SF10)" 3600 bash -c \
  'set -o pipefail; python bench.py | tee BENCH_r05_dev.json | tee -a AB_FUSION_r05.log'
step "post-fusion starjoin (dense probe)" 3600 bash -c \
  'set -o pipefail; python bench_suite.py starjoin 2>&1 | tail -1 | tee -a AB_FUSION_r05.log'
step "post-fusion full22 SF1 (parquet register)" 5400 bash -c \
  'set -o pipefail; python bench_suite.py full22 2>&1 | tail -1 | tee -a AB_FUSION_r05.log'
step "post-fusion q3 (auto route: cpu-join + device agg)" 5400 bash -c \
  'set -o pipefail; python bench_suite.py q3 2>&1 | tail -1 | tee -a AB_FUSION_r05.log'
# keyed pinned: q3's keyed sort is single-key and now rides the packed
# u64 form — this A/B says whether packing moved the 0.036x chip number
step "A/B q3 keyed (packed sort)" 3600 bash -c \
  'set -o pipefail; BENCH_HIGHCARD_MODE=device BENCH_Q3_SF=1 python bench_suite.py q3 2>&1 | tail -1 | tee -a AB_FUSION_r05.log'
# window at reduced scale first: the full 2e7 config blocked the chip for
# 55 min in the main capture — prove the device path at 2e6 before
# risking the big shape again
step "post-fusion window 2e6" 1800 bash -c \
  'set -o pipefail; BENCH_WINDOW_N=2e6 BENCH_WINDOW_PARTS=5e3 python bench_suite.py window 2>&1 | tail -1 | tee -a AB_FUSION_r05.log'
step "kernel microbench grid" 5400 \
  python benchmarks/kernels.py --iters 3 --host-encode --out KERNELBENCH_r05.json

# LAST (longest, and the crash-fixed path): BASELINE config #5 has no
# chip row at all — highcard questions take the C++ hash handoff, the
# low-card gang now degrades instead of dying on a compile-helper loss
step "post-fusion h2o G1_1e8" 7200 python bench_suite.py h2o

if [ "$fails" -gt 0 ]; then
  echo "== post-fusion capture FINISHED WITH $fails FAILED STEP(S) =="
  exit 1
fi
echo "== post-fusion capture complete =="
