#!/bin/sh
# LoC diagnostic (recorded so the round verdicts can re-run the exact
# command — round-2 advisor finding: the numbers weren't reproducible).
#
# Counts non-blank lines of hand-written source: python + C++ + proto,
# excluding generated protobuf modules (proto/gen), tests, and harnesses.
cd "$(dirname "$0")/.."
count() { cat "$@" 2>/dev/null | grep -vc '^[[:space:]]*$'; }

echo "repo core (arrow_ballista_tpu python, excl. proto/gen):"
count $(find arrow_ballista_tpu -name "*.py" ! -path "*/proto/gen/*")
echo "native C++:"
count $(find arrow_ballista_tpu/native \( -name "*.cc" -o -name "*.h" \))
echo "proto definitions:"
count arrow_ballista_tpu/proto/*.proto
echo "tests:"
count $(find tests -name "*.py")
echo "benchmarks + entry points:"
count $(find benchmarks -name "*.py") bench.py bench_suite.py __graft_entry__.py
