#!/usr/bin/env bash
# Probe-watch trip-wire (VERDICT r4 item 1): poll the device probe every
# ~10 min in a subprocess with a hard timeout (never touching jax in this
# process), and on the FIRST successful probe run the serialized capture
# protocol — quick first (so the headline numbers exist even if the
# tunnel re-wedges), then full.
#
#   nohup bash dev/watch_chip.sh > dev/watch_chip.log 2>&1 &
#
# Writes dev/watch_chip.status after every probe so a human (or the
# build loop) can check progress without touching the chip.

set -u
cd "$(dirname "$0")/.."

STATUS=dev/watch_chip.status
INTERVAL="${WATCH_INTERVAL_S:-600}"

probe_once() {
  timeout 200 python -c "
from benchmarks.device_guard import probe_backend
import sys
p = probe_backend(180)
print('probe:', p)
sys.exit(0 if p not in (None, 'timeout', 'cpu') else 1)
"
}

n=0
while true; do
  n=$((n + 1))
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  if out=$(probe_once 2>&1); then
    echo "$ts probe#$n OK: $out" | tee -a "$STATUS"
    echo "$ts starting capture (quick)" | tee -a "$STATUS"
    bash dev/capture_chip.sh quick >> dev/capture_quick.log 2>&1
    rc=$?
    echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) quick capture rc=$rc" | tee -a "$STATUS"
    if [ "$rc" -eq 0 ]; then
      echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) starting capture (full)" | tee -a "$STATUS"
      bash dev/capture_chip.sh full >> dev/capture_full.log 2>&1
      frc=$?
      echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) full capture rc=$frc" | tee -a "$STATUS"
      if [ "$frc" -eq 0 ]; then
        echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) DONE" | tee -a "$STATUS"
        exit 0
      fi
      # full capture had failed steps — keep watching so a later probe
      # window can rerun it (quick artifacts are already on disk)
    fi
    # quick capture failed (tunnel re-wedged mid-run?) — keep watching
  else
    echo "$ts probe#$n unavailable: $out" >> "$STATUS"
  fi
  sleep "$INTERVAL"
done
