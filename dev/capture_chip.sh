#!/usr/bin/env bash
# Serialized chip-measurement protocol (VERDICT r3 item 1).
#
# Run ONLY when the device probe answers (the tunnel wedges if a process
# is killed mid-device-op, so every job gets a generous timeout and
# nothing here SIGTERMs an in-flight device op).  One job at a time —
# the box has a single CPU core and an exclusive chip.
#
#   bash dev/capture_chip.sh            # full capture (~1-2h)
#   bash dev/capture_chip.sh quick      # bench.py + q6/q3 only
#
# Outputs: BENCH_r04_dev.json (bench.py line), BENCH_SUITE_r04.json,
# KERNELBENCH_r04.json, AB_r04.log (A/B knob runs).

set -u
cd "$(dirname "$0")/.."

probe() {
  timeout 200 python -c "
from benchmarks.device_guard import probe_backend
import sys
p = probe_backend(180)
print('probe:', p)
sys.exit(0 if p not in (None, 'timeout', 'cpu') else 1)
"
}

echo "== probing device =="
if ! probe; then
  echo "device unavailable — aborting capture (nothing written)"
  exit 2
fi

mode="${1:-full}"

echo "== bench.py (q1 SF10) =="
timeout 3600 python bench.py | tee BENCH_r04_dev.json

echo "== suite: q6 =="
timeout 3600 python bench_suite.py q6
echo "== suite: q3 =="
timeout 5400 python bench_suite.py q3

if [ "$mode" = "full" ]; then
  echo "== suite: starjoin =="
  timeout 3600 python bench_suite.py starjoin
  echo "== suite: full22 =="
  timeout 5400 python bench_suite.py full22
  echo "== suite: window =="
  timeout 3600 python bench_suite.py window
  echo "== suite: h2o =="
  timeout 7200 python bench_suite.py h2o

  echo "== A/B: q3 agg algorithm sort vs scatter ==" | tee AB_r04.log
  BENCH_AGG_ALGO=sort timeout 5400 python bench_suite.py q3 2>&1 | tail -1 | tee -a AB_r04.log
  BENCH_AGG_ALGO=scatter timeout 5400 python bench_suite.py q3 2>&1 | tail -1 | tee -a AB_r04.log

  echo "== A/B: h2o highcard routing cpu vs auto(keyed) ==" | tee -a AB_r04.log
  # highcard_mode=cpu reproduces the pre-keyed C++-hash-aggregate handoff
  BENCH_HIGHCARD_MODE=cpu BENCH_H2O_N=1e8 timeout 7200 python bench_suite.py h2o 2>&1 | tail -1 | tee -a AB_r04.log

  echo "== kernel microbench grid =="
  timeout 5400 python benchmarks/kernels.py --iters 3 --host-encode --out KERNELBENCH_r04.json
fi

echo "== capture complete =="
