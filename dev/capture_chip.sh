#!/usr/bin/env bash
# Serialized chip-measurement protocol (VERDICT r3 item 1).
#
# Run ONLY when the device probe answers (the tunnel wedges if a process
# is killed mid-device-op, so every job gets a generous timeout and
# nothing here SIGTERMs an in-flight device op).  One job at a time —
# the box has a single CPU core and an exclusive chip.
#
#   bash dev/capture_chip.sh            # full capture (~1-2h)
#   bash dev/capture_chip.sh quick      # bench.py + q6/q3 only
#
# Outputs: BENCH_r05_dev.json (bench.py line), BENCH_SUITE_r05.json,
# KERNELBENCH_r05.json, AB_r05.log (A/B knob runs).
#
# Exits nonzero if ANY step fails or times out, so the watch loop can
# tell a real capture from a re-wedged tunnel and keep polling.

set -u -o pipefail
cd "$(dirname "$0")/.."

fails=0
step() {
  # step <name> <timeout_s> <cmd...>  — never aborts the sequence, but
  # records the failure so the script's exit code reflects it
  local name="$1" t="$2"
  shift 2
  echo "== $name =="
  timeout "$t" "$@"
  local rc=$?
  if [ "$rc" -ne 0 ]; then
    echo "!! step '$name' failed (rc=$rc)"
    fails=$((fails + 1))
  fi
}

probe() {
  timeout 200 python -c "
from benchmarks.device_guard import probe_backend
import sys
p = probe_backend(180)
print('probe:', p)
sys.exit(0 if p not in (None, 'timeout', 'cpu') else 1)
"
}

echo "== probing device =="
if ! probe; then
  echo "device unavailable — aborting capture (nothing written)"
  exit 2
fi

mode="${1:-full}"

step "bench.py (q1 SF10)" 3600 bash -c 'set -o pipefail; python bench.py | tee BENCH_r05_dev.json'

step "suite: q6" 3600 python bench_suite.py q6
step "suite: q3" 5400 python bench_suite.py q3

if [ "$mode" = "full" ]; then
  step "suite: starjoin" 3600 python bench_suite.py starjoin
  step "suite: full22" 5400 python bench_suite.py full22
  step "suite: window" 3600 python bench_suite.py window
  step "suite: h2o" 7200 python bench_suite.py h2o

  echo "== A/B: q3 agg algorithm sort vs scatter ==" | tee AB_r05.log
  step "A/B q3 sort" 5400 bash -c \
    'set -o pipefail; BENCH_AGG_ALGO=sort python bench_suite.py q3 2>&1 | tail -1 | tee -a AB_r05.log'
  step "A/B q3 scatter" 5400 bash -c \
    'set -o pipefail; BENCH_AGG_ALGO=scatter python bench_suite.py q3 2>&1 | tail -1 | tee -a AB_r05.log'

  echo "== A/B: h2o highcard routing cpu vs auto(keyed) ==" | tee -a AB_r05.log
  # highcard_mode=cpu reproduces the pre-keyed C++-hash-aggregate handoff
  step "A/B h2o highcard=cpu" 7200 bash -c \
    'set -o pipefail; BENCH_HIGHCARD_MODE=cpu BENCH_H2O_N=1e8 python bench_suite.py h2o 2>&1 | tail -1 | tee -a AB_r05.log'

  step "kernel microbench grid" 5400 \
    python benchmarks/kernels.py --iters 3 --host-encode --out KERNELBENCH_r05.json
fi

if [ "$fails" -gt 0 ]; then
  echo "== capture FINISHED WITH $fails FAILED STEP(S) =="
  exit 1
fi
echo "== capture complete =="
