#!/usr/bin/env bash
# Tier-1 verify: the exact command the ROADMAP pins (CPU-pinned jax, slow
# tests excluded, collection errors tolerated so one broken module can't
# hide the rest).  Prints DOTS_PASSED= the count of passing tests and
# exits with pytest's status.
#
# Usage: dev/tier1.sh [--bench-smoke] [--chaos-smoke] [extra pytest args...]
#   --bench-smoke  additionally run the shuffle write/fetch micro-benches
#                  on tiny inputs after the tests — a compile/regression
#                  smoke for the benchmark harnesses themselves, NOT a
#                  measurement and NOT part of default tier-1.
#   --chaos-smoke  additionally run the bounded chaos soaks (pytest
#                  -m chaos): executors are drained/killed at random
#                  during small queries, and the scheduler itself is
#                  SIGKILLed mid-burst and restarted (admission-WAL
#                  replay + orphan-fleet adoption) — everything must
#                  still complete with correct results.  Seeded via
#                  BALLISTA_CHAOS_SEED.
set -o pipefail
cd "$(dirname "$0")/.."
BENCH_SMOKE=0
CHAOS_SMOKE=0
while :; do
  case "$1" in
    --bench-smoke) BENCH_SMOKE=1; shift ;;
    --chaos-smoke) CHAOS_SMOKE=1; shift ;;
    *) break ;;
  esac
done
# proto drift gate: a NEW_FIELDS edit without regeneration (or a
# generated field missing from ballista.proto) fails fast, before tests
timeout -k 10 60 env JAX_PLATFORMS=cpu python dev/regen_proto.py --check || exit 1
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  "$@" 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
if [ "$BENCH_SMOKE" = "1" ]; then
  echo "--- bench smoke (tiny inputs; compile check, not a measurement) ---"
  timeout -k 10 120 env JAX_PLATFORMS=cpu python - <<'EOF'
import json
from benchmarks.shuffle_fetch import run_fetch_bench
from benchmarks.shuffle_write import run_write_bench

print(json.dumps({"bench_smoke": "shuffle_fetch",
                  **run_fetch_bench(n_locations=4, mb_per_location=0.5,
                                    batch_rows=4096, concurrency=2)}))
print(json.dumps({"bench_smoke": "shuffle_write",
                  **run_write_bench(n_batches=4, rows_per_batch=8192,
                                    n_out=4, compression="zstd", iters=1)}))
EOF
  smoke_rc=$?
  [ $rc -eq 0 ] && rc=$smoke_rc
  timeout -k 10 120 env JAX_PLATFORMS=cpu python - <<'EOF'
import json
from benchmarks.shuffle_locality import run_locality_smoke

# locality A/B on tiny inputs: all three transports bit-identical, the
# local leg zero-copy, the batched leg fewer round trips
print(json.dumps({"bench_smoke": "shuffle_locality",
                  **run_locality_smoke()}))
EOF
  smoke_rc=$?
  [ $rc -eq 0 ] && rc=$smoke_rc
  timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import json
from benchmarks.aqe_starjoin import run_aqe_smoke

# AQE A/B on tiny inputs: asserts bit-identical results static-vs-
# adaptive and that the tiny-partition aggregate actually coalesced
print(json.dumps({"bench_smoke": "aqe", **run_aqe_smoke()}))
EOF
  smoke_rc=$?
  [ $rc -eq 0 ] && rc=$smoke_rc
  timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import json
from benchmarks.keyed_path import run_keyed_smoke

# keyed device-path A/B on tiny inputs: all legs bit-identical, the
# fused leg device-encodes with zero host group encode
print(json.dumps({"bench_smoke": "keyed_path", **run_keyed_smoke()}))
EOF
  smoke_rc=$?
  [ $rc -eq 0 ] && rc=$smoke_rc
  timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import json
from benchmarks.concurrent_clients import run_admission_smoke

# admission smoke: saturate 2 slots with 6 jobs from two weighted pools
# over the real wire — fair-share release order, zero failures, and
# job_queued/job_admitted journal events asserted inside
print(json.dumps({"bench_smoke": "admission", **run_admission_smoke()}))
EOF
  smoke_rc=$?
  [ $rc -eq 0 ] && rc=$smoke_rc
  timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import json
from benchmarks.pipelined_stage import run_pipelining_smoke

# pipelined-execution smoke: tiny 2-executor job with one manufactured
# slow map task — the pipelined leg's first reduce dispatch must precede
# the last map commit and results must be bit-identical to the barrier
# leg (asserted inside)
print(json.dumps({"bench_smoke": "pipelined", **run_pipelining_smoke()}))
EOF
  smoke_rc=$?
  [ $rc -eq 0 ] && rc=$smoke_rc
  timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import json
from benchmarks.obs_doctor import run_doctor_smoke

# query-doctor smoke: tiny standalone job with a manufactured straggler
# — the critical_path endpoint's category sum must land within
# tolerance of wall-clock and the doctor must fire skewed_stage with
# evidence naming the real stage/partition (asserted inside)
print(json.dumps({"bench_smoke": "doctor", **run_doctor_smoke()}))
EOF
  smoke_rc=$?
  [ $rc -eq 0 ] && rc=$smoke_rc
  timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import json
from benchmarks.elastic_burst import run_autoscaler_smoke

# autoscaler smoke: tiny burst against a 1-executor elastic cluster —
# one scale-out, one drain-based scale-in after the idle cooldown, zero
# failed tasks, autoscale_decision/executor_launched/executor_retired
# journal events present (asserted inside)
print(json.dumps({"bench_smoke": "autoscaler", **run_autoscaler_smoke()}))
EOF
  smoke_rc=$?
  [ $rc -eq 0 ] && rc=$smoke_rc
  timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import json
from benchmarks.plan_cache import run_plan_cache_smoke

# plan-cache smoke: repeat submission of an identical query must serve
# from the fingerprint cache with zero dispatched tasks and identical
# rows; re-registering different data must invalidate; the knob-off leg
# must never touch the cache (asserted inside)
print(json.dumps({"bench_smoke": "plan_cache", **run_plan_cache_smoke()}))
EOF
  smoke_rc=$?
  [ $rc -eq 0 ] && rc=$smoke_rc
  timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import json
from benchmarks.whole_stage_fusion import run_fusion_smoke

# whole-stage fusion smoke: tiny q3-shaped + scan-heavy stages — the
# fused leg must plan ONE segment covering >1 operator and execute it
# as ONE dispatch per task (zero host round-trips between fused ops),
# bit-identical to the knob-off per-batch leg (asserted inside)
print(json.dumps({"bench_smoke": "whole_stage_fusion",
                  **run_fusion_smoke()}))
EOF
  smoke_rc=$?
  [ $rc -eq 0 ] && rc=$smoke_rc
  echo "--- benchmark trajectory (root BENCH_*.json snapshots) ---"
  timeout -k 10 60 python dev/bench_report.py || true
fi
if [ "$CHAOS_SMOKE" = "1" ]; then
  echo "--- chaos smoke (bounded kill/drain + scheduler-kill soaks) ---"
  timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m chaos \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly
  chaos_rc=$?
  [ $rc -eq 0 ] && rc=$chaos_rc
fi
exit $rc
