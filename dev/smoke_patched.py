"""Smoke-test the patched package copy (dev/pkgcopy) on the CPU backend
before overlaying the live package: fused single-dispatch runner, device
tail masks, dense-probe join, keyed pin, routing flip, cache hits."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "pkgcopy"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402

from arrow_ballista_tpu import BallistaConfig, SessionContext  # noqa: E402
from arrow_ballista_tpu.catalog import MemoryTable  # noqa: E402
from arrow_ballista_tpu.ops.stage_compiler import TpuStageExec  # noqa: E402

assert "pkgcopy" in sys.modules["arrow_ballista_tpu"].__file__, (
    "smoke must import the PATCHED copy, got %s"
    % sys.modules["arrow_ballista_tpu"].__file__
)


def ctx(tpu, **extra):
    s = {
        "ballista.tpu.enable": "true" if tpu else "false",
        "ballista.tpu.min_rows": "0",
        "ballista.shuffle.partitions": "1",
    }
    s.update({k: str(v) for k, v in extra.items()})
    return SessionContext(BallistaConfig(s))


def metrics(plan):
    agg = {}
    stack = [plan]
    while stack:
        n = stack.pop()
        if isinstance(n, TpuStageExec):
            for k, v in n.metrics.values.items():
                agg[k] = agg.get(k, 0) + v
        stack.extend(n.children())
    return agg


def run(c, sql):
    df = c.sql(sql)
    plan = df.physical_plan()
    return c.execute(plan), metrics(plan)


def check(name, sql, tables, expect_metric=None, absent_metric=None,
          **extra):
    cc, ct = ctx(False), ctx(True, **extra)
    for nm, t in tables.items():
        cc.register_table(nm, MemoryTable.from_table(t, 1))
        ct.register_table(nm, MemoryTable.from_table(t, 1))
    want, _ = run(cc, sql)
    got, m = run(ct, sql)
    key = [(c0, "ascending") for c0 in want.column_names
           if not pa.types.is_floating(want.schema.field(c0).type)]
    want, got = want.sort_by(key), got.sort_by(key)
    assert want.num_rows == got.num_rows, (name, want.num_rows, got.num_rows)
    for col in want.column_names:
        for x, y in zip(want.column(col).to_pylist(),
                        got.column(col).to_pylist()):
            if isinstance(x, float) and x is not None and y is not None:
                assert abs(x - y) <= 1e-9 * max(abs(x), abs(y), 1.0), (
                    name, col, x, y)
            else:
                assert x == y, (name, col, x, y)
    if expect_metric:
        for em in ([expect_metric] if isinstance(expect_metric, str)
                   else expect_metric):
            assert m.get(em, 0) >= 1, (name, em, m)
    if absent_metric:
        assert m.get(absent_metric, 0) == 0, (name, absent_metric, m)
    print("ok:", name, {k: v for k, v in m.items() if not k.endswith("_ns")})
    return m


rng = np.random.default_rng(0)
n = 6000
t = pa.table({
    "k": pa.array(rng.integers(0, 7, n), pa.int64()),
    "v": pa.array(rng.uniform(-100, 100, n)),
    "q": pa.array(rng.integers(1, 50, n).astype(np.float64)),
})
tn = pa.table({
    "k": t.column("k"),
    "v": pa.array([None if x > 80 else x
                   for x in t.column("v").to_pylist()], pa.float64()),
    "q": t.column("q"),
})

check("grouped fused", "select k, sum(v), count(v), min(q), max(v) "
      "from t group by k", {"t": t}, expect_metric="fused_dispatches")
check("scalar fused", "select sum(v), count(*), min(v) from t where q < 25",
      {"t": t}, expect_metric="fused_dispatches")
check("nulls fused", "select k, sum(v), count(v) from t group by k",
      {"t": tn}, expect_metric="fused_dispatches")

# multi-batch + capacity growth
big = pa.table({
    "k": pa.array(rng.integers(0, 3000, 30000), pa.int64()),
    "v": pa.array(rng.uniform(-10, 10, 30000)),
    "q": pa.array(rng.integers(1, 50, 30000).astype(np.float64)),
})
check("growth fused", "select k, sum(v), count(v) from big group by k",
      {"big": big}, expect_metric="fused_dispatches",
      **{"ballista.batch.size": 4096})

# cache hit second run
cthit = ctx(True)
cthit.register_table("t", MemoryTable.from_table(t, 1))
r1, _ = run(cthit, "select k, sum(v) from t group by k")
r2, m2 = run(cthit, "select k, sum(v) from t group by k")
assert m2.get("cache_hits", 0) >= 1 and m2.get("fused_dispatches", 0) >= 1, m2
assert r1.sort_by([("k", "ascending")]).equals(
    r2.sort_by([("k", "ascending")]))
print("ok: cache hit fused", m2.get("cache_hits"))

# dense join (contiguous, offset, gappy) + wide-span sorted fallback
m_dim = 500
dim = pa.table({
    "pk": pa.array(np.arange(100, 100 + m_dim), pa.int64()),
    "dv": pa.array(rng.uniform(0.5, 1.5, m_dim)),
    "dg": pa.array((np.arange(m_dim) % 5).astype(np.int64)),
})
fact = pa.table({
    "fk": pa.array(rng.integers(0, 800, 5000), pa.int64()),
    "g": pa.array(rng.integers(0, 5, 5000), pa.int64()),
    "x": pa.array(rng.uniform(0, 1, 5000)),
})
jm = check("dense join",
           "select g, sum(x * dv), count(*) from dim, fact where pk = fk "
           "group by g", {"dim": dim, "fact": fact},
           expect_metric="dense_join", absent_metric="tpu_fallback")
assert jm.get("join_fallback", 0) == 0, jm

wide = pa.table({
    "pk": pa.array((np.arange(1024) << 18).astype(np.int64)),
    "dv": pa.array(rng.uniform(0.5, 1.5, 1024)),
    "dg": pa.array((np.arange(1024) % 5).astype(np.int64)),
})
wfact = pa.table({
    # half the probes hit real keys, half are uniform misses
    "fk": pa.array(np.concatenate([
        (rng.integers(0, 1024, 2500) << 18),
        rng.integers(0, 1 << 28, 2500),
    ]).astype(np.int64)),
    "g": pa.array(rng.integers(0, 5, 5000), pa.int64()),
    "x": pa.array(rng.uniform(0, 1, 5000)),
})
wm = check("wide-span sorted join",
      "select g, sum(x * dv), count(*) from wide, wfact where pk = fk "
      "group by g", {"wide": wide, "wfact": wfact},
      absent_metric="tpu_fallback")
assert wm.get("dense_join", 0) == 0, wm

# keyed path still works when PINNED
hk = pa.table({
    "k": pa.array(rng.integers(0, 400000, 300000), pa.int64()),
    "v": pa.array(rng.uniform(-10, 10, 300000)),
})
mk = check("keyed pinned", "select k, sum(v), count(*) from hk group by k",
           {"hk": hk}, expect_metric="keyed_path",
           **{"ballista.tpu.highcard_mode": "device"})

# auto no longer routes keyed: same shape without the pin must take the
# C++ hash handoff (highcard_fallback), not the keyed path
ma = check("auto highcard -> hash handoff",
           "select k, sum(v), count(*) from hk group by k", {"hk": hk})
assert ma.get("keyed_path", 0) == 0, ma
assert ma.get("highcard_fallback", 0) >= 1, ma

print("SMOKE PASSED")
