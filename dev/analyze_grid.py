"""Turn KERNELBENCH grid rows into routing-threshold recommendations.

The r04/r05 verdict discipline: routing constants must cite a measured
artifact, not a guess.  This reads one or more KERNELBENCH_*.json files
and prints, per platform found in the rows:

* the matmul->sort capacity crossover per row count (tunes
  kernels._MATMUL_MAX_CAP / _MATMUL_MAX_ELEMS);
* the scatter/sort/keyed winner per (rows, capacity) cell (tunes
  segment_algo and the highcard route);
* sort cost vs operand count + the packed-u64 ratio (validates the
  packed-sort rework);
* dispatch/fetch latency floors (the q6 economics).

Usage: python dev/analyze_grid.py KERNELBENCH_r05.json [more.json ...]
"""

from __future__ import annotations

import json
import sys
from collections import defaultdict


def load(paths):
    rows = []
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
    return rows


def main() -> None:
    paths = sys.argv[1:] or ["KERNELBENCH_r05.json"]
    rows = load(paths)
    by_platform = defaultdict(list)
    for r in rows:
        by_platform[r.get("device_platform", "?")].append(r)

    for platform, rs in by_platform.items():
        print(f"\n=== platform: {platform} "
              f"({'FALLBACK — not chip data' if any('error' in r for r in rs) else 'clean'}) ===")

        cells = defaultdict(dict)  # (rows, cap) -> algo -> rows/s
        for r in rs:
            if r.get("bench") == "segment_reduce" and "rows_per_sec" in r:
                cells[(r["rows"], r["capacity"])][r["algo"]] = r["rows_per_sec"]

        if cells:
            print("segment_reduce winner per (rows, capacity):")
            crossover = {}
            for (n, cap), algos in sorted(cells.items()):
                win = max(algos, key=algos.get)
                line = "  ".join(
                    f"{a}={v / 1e6:.1f}M" for a, v in sorted(algos.items())
                )
                print(f"  rows={n:>9} cap={cap:>8}: winner={win:<8} {line}")
                if "matmul" in algos and "sort" in algos:
                    better = algos["matmul"] > algos["sort"]
                    cur = crossover.get(n)
                    if better and (cur is None or cap > cur):
                        crossover[n] = cap
            for n, cap in sorted(crossover.items()):
                print(f"  -> matmul still wins at cap={cap} for rows={n}: "
                      f"set _MATMUL_MAX_CAP >= {cap} "
                      f"(_MATMUL_MAX_ELEMS >= {n * cap:.0e})")

        sorts = [r for r in rs if r.get("bench") == "sort_operands"
                 and "rows_per_sec" in r]
        if sorts:
            print("sort cost vs operands:")
            base = {}
            for r in sorted(sorts, key=lambda r: (r["rows"], r["operands"])):
                key = (r["rows"], "u64x1")
                if r["operands"] == "u64x1":
                    base[r["rows"]] = r["rows_per_sec"]
            for r in sorted(sorts, key=lambda r: (r["rows"], r["operands"])):
                rel = (
                    f"  ({base[r['rows']] / r['rows_per_sec']:.1f}x slower "
                    f"than u64x1)" if r["operands"] != "u64x1"
                    and r["rows"] in base else ""
                )
                print(f"  rows={r['rows']:>9} {r['operands']:>6}: "
                      f"{r['rows_per_sec'] / 1e6:6.1f}M rows/s{rel}")

        lat = [r for r in rs if r.get("bench") == "tunnel_latency"
               and "sec" in r]
        for r in lat:
            print(f"latency {r['metric']}: {r['sec'] * 1000:.2f} ms")
        if lat:
            one = next((r["sec"] for r in lat
                        if r["metric"] == "dispatch_plus_fetch"), None)
            if one:
                print(f"  -> per-query floor ~{one * 1000:.0f} ms: a query "
                      f"must beat the CPU by more than this to win; the "
                      f"fused runner exists to pay it exactly once")

        enc = [r for r in rs if r.get("bench") == "host_encode"
               and "rows_per_sec" in r]
        if enc:
            print("host encode:")
            for r in sorted(enc, key=lambda r: (r["rows"], r["algo"])):
                print(f"  rows={r['rows']:>9} {r['algo']:>12}: "
                      f"{r['rows_per_sec'] / 1e6:6.1f}M rows/s")


if __name__ == "__main__":
    main()
