"""Turn KERNELBENCH grid rows into routing-threshold recommendations.

The r04/r05 verdict discipline: routing constants must cite a measured
artifact, not a guess.  This reads one or more KERNELBENCH_*.json files
and prints, per platform found in the rows:

* the matmul->sort capacity crossover per row count (tunes
  routing ``matmul_max_cap`` / ``matmul_max_elems``);
* the scatter/sort/keyed winner per (rows, capacity) cell (tunes
  segment_algo and the highcard route);
* sort cost vs operand count + the packed-u64 ratio (validates the
  packed-sort rework);
* dispatch/fetch latency floors (the q6 economics).

``--emit <path>`` additionally writes the recommendations as the
machine-readable routing table ``arrow_ballista_tpu/ops/routing.py``
loads at import (schema ``ballista.routing/v1``; the emit schema is
pinned by tests/test_routing_table.py).  Fields the grid has no
evidence for keep the builtin defaults, with the per-field basis
recorded under ``evidence`` so the artifact documents exactly what was
measured vs inherited.

Usage: python dev/analyze_grid.py KERNELBENCH_r05.json [more.json ...]
           [--emit arrow_ballista_tpu/ops/routing_table.json]
"""

from __future__ import annotations

import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load(paths):
    rows = []
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
    return rows


def emit_routing_table(rows, inputs) -> dict:
    """Routing-table document (ballista.routing/v1) from grid rows.

    Per platform present in the rows:

    * ``matmul_max_cap`` / ``matmul_max_elems`` — largest capacity where
      the matmul segment reduction beat BOTH sort and scatter at EVERY
      measured row count (a capacity crossover must hold across row
      counts — one row-count outlier, e.g. BLAS threading kicking in at
      8M rows on the cpu box, must not move a threshold applied to every
      batch size); default when no capacity wins consistently.
    * ``keyed_route_auto`` — True only when the keyed reduction (the
      fused ``keyed_fused`` cell when the grid has it, else the
      pre-fusion ``keyed`` cell) beats every alternative at the
      high-cardinality cells (capacity >= highcard_min_groups); this is
      what lets ``auto`` route groups~rows plans to the fused keyed
      path on platforms where the measurement supports it.
    * detector bounds (``highcard_min_groups`` / ``highcard_ratio``)
      keep the builtin defaults — no grid bench measures the detector
      itself yet.
    * whole-stage fusion bounds (ISSUE 19): ``fusion_min_rows`` — the
      amortization floor below which one fused dispatch costs more than
      it saves — is judged from the keyed_fused-vs-keyed pairs (the
      one-dispatch vs 3-dispatch form of the SAME reduction, the grid's
      direct measurement of dispatch-fusion payoff): the floor becomes
      the smallest measured row count from which the fused form wins at
      every larger measured row count, and stays at the builtin when
      the fused form already wins at the grid's smallest cell (the grid
      cannot see below its own floor).  ``fusion_max_ops`` keeps the
      builtin default — it is the _FUSED_MAX_ENTRIES unroll discipline
      applied to operator count, and no grid cell measures op-count
      scaling yet.
    """
    from arrow_ballista_tpu.ops import routing

    by_platform = defaultdict(list)
    for r in rows:
        by_platform[r.get("device_platform", "?")].append(r)

    platforms = {}
    for platform, rs in sorted(by_platform.items()):
        vals = dict(routing._DEFAULTS)
        evidence = {
            k: "builtin default (no grid evidence)" for k in vals
        }
        cells = defaultdict(dict)
        for r in rs:
            if r.get("bench") == "segment_reduce" and "rows_per_sec" in r:
                cells[(r["rows"], r["capacity"])][r["algo"]] = r[
                    "rows_per_sec"
                ]
        # per-capacity verdict: matmul must win at EVERY measured row
        # count for that capacity to count toward the crossover — the
        # threshold steers every batch size, so one row-count outlier
        # cannot set it
        mm_by_cap: dict = {}
        for (n, cap), algos in sorted(cells.items()):
            others = [v for a, v in algos.items() if a != "matmul"]
            if "matmul" not in algos or not others:
                continue
            won = algos["matmul"] > max(others)
            all_won, elems = mm_by_cap.get(cap, (True, 0))
            mm_by_cap[cap] = (all_won and won, max(elems, n * cap))
        mm_caps = [c for c, (won, _e) in mm_by_cap.items() if won]
        if mm_caps:
            vals["matmul_max_cap"] = max(mm_caps)
            vals["matmul_max_elems"] = max(
                mm_by_cap[c][1] for c in mm_caps
            )
            evidence["matmul_max_cap"] = evidence["matmul_max_elems"] = (
                "largest capacity where matmul beat sort+scatter at "
                "every measured row count"
            )
        else:
            evidence["matmul_max_cap"] = evidence["matmul_max_elems"] = (
                "builtin default: matmul won no measured capacity "
                "consistently across row counts on this platform"
            )
        highcard = [
            (k, algos)
            for k, algos in cells.items()
            if k[1] >= vals["highcard_min_groups"] and len(algos) > 1
        ]
        if highcard:

            def keyed_best(algos: dict) -> bool:
                # the fused cell is the production shape; the pre-fusion
                # 'keyed' cell stands in on grids captured before it
                kv = algos.get("keyed_fused", algos.get("keyed"))
                return kv is not None and kv == max(algos.values())

            keyed_wins = all(keyed_best(algos) for _k, algos in highcard)
            vals["keyed_route_auto"] = bool(keyed_wins)
            evidence["keyed_route_auto"] = (
                "keyed(_fused) %s every alternative at the %d "
                "high-cardinality segment_reduce cell(s)"
                % ("beat" if keyed_wins else "lost to", len(highcard))
            )
        # whole-stage fusion amortization floor: keyed_fused vs keyed is
        # the grid's one-dispatch vs 3-dispatch pair for the same
        # reduction — where the fused form wins, a fused dispatch pays
        # for itself at that input size
        fused_won: dict = {}
        for (n, _cap), algos in cells.items():
            if "keyed_fused" in algos and "keyed" in algos:
                ok = algos["keyed_fused"] >= algos["keyed"]
                fused_won[n] = fused_won.get(n, True) and ok
        evidence["fusion_max_ops"] = (
            "builtin default: the _FUSED_MAX_ENTRIES unroll discipline "
            "applied to operator count (no grid cell measures op-count "
            "scaling)"
        )
        if fused_won:
            sizes = sorted(fused_won)
            # smallest size from which the fused form wins at every
            # larger measured size
            floor = None
            for i, n in enumerate(sizes):
                if all(fused_won[m] for m in sizes[i:]):
                    floor = n
                    break
            if floor is None:
                won = [n for n in sizes if fused_won[n]]
                lost = [n for n in sizes if not fused_won[n]]
                if won:
                    evidence["fusion_min_rows"] = (
                        "builtin default kept: no stable amortization "
                        "floor — keyed_fused beat the 3-dispatch keyed "
                        "form at %s rows but lost at %s rows, so the "
                        "win does not hold through the largest "
                        "measured size"
                        % (
                            ", ".join(str(n) for n in won),
                            ", ".join(str(n) for n in lost),
                        )
                    )
                else:
                    evidence["fusion_min_rows"] = (
                        "builtin default: keyed_fused never beat the "
                        "3-dispatch keyed form at any measured row "
                        "count (%s rows)"
                        % ", ".join(str(n) for n in sizes)
                    )
            elif floor == sizes[0]:
                evidence["fusion_min_rows"] = (
                    "builtin default kept: keyed_fused beat the "
                    "3-dispatch keyed form at every measured row count "
                    "(smallest cell %d rows; the grid cannot see below "
                    "its own floor)" % floor
                )
            else:
                vals["fusion_min_rows"] = int(floor)
                evidence["fusion_min_rows"] = (
                    "smallest measured row count from which keyed_fused "
                    "beat the 3-dispatch keyed form at every larger "
                    "size (lost below %d rows)" % floor
                )
        platforms[platform] = {**vals, "evidence": evidence}

    return {
        "schema": routing.SCHEMA,
        "generated_by": "dev/analyze_grid.py --emit",
        "inputs": [os.path.basename(p) for p in inputs],
        "platforms": platforms,
    }


def main() -> None:
    args = sys.argv[1:]
    emit_path = None
    if "--emit" in args:
        i = args.index("--emit")
        emit_path = args[i + 1]
        args = args[:i] + args[i + 2:]
    paths = args or ["KERNELBENCH_r05.json"]
    rows = load(paths)
    if emit_path:
        doc = emit_routing_table(rows, paths)
        with open(emit_path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote routing table -> {emit_path}")
    by_platform = defaultdict(list)
    for r in rows:
        by_platform[r.get("device_platform", "?")].append(r)

    for platform, rs in by_platform.items():
        print(f"\n=== platform: {platform} "
              f"({'FALLBACK — not chip data' if any('error' in r for r in rs) else 'clean'}) ===")

        cells = defaultdict(dict)  # (rows, cap) -> algo -> rows/s
        for r in rs:
            if r.get("bench") == "segment_reduce" and "rows_per_sec" in r:
                cells[(r["rows"], r["capacity"])][r["algo"]] = r["rows_per_sec"]

        if cells:
            print("segment_reduce winner per (rows, capacity):")
            crossover = {}
            for (n, cap), algos in sorted(cells.items()):
                win = max(algos, key=algos.get)
                line = "  ".join(
                    f"{a}={v / 1e6:.1f}M" for a, v in sorted(algos.items())
                )
                print(f"  rows={n:>9} cap={cap:>8}: winner={win:<8} {line}")
                if "matmul" in algos and "sort" in algos:
                    better = algos["matmul"] > algos["sort"]
                    cur = crossover.get(n)
                    if better and (cur is None or cap > cur):
                        crossover[n] = cap
            for n, cap in sorted(crossover.items()):
                print(f"  -> matmul still wins at cap={cap} for rows={n}: "
                      f"set _MATMUL_MAX_CAP >= {cap} "
                      f"(_MATMUL_MAX_ELEMS >= {n * cap:.0e})")

        sorts = [r for r in rs if r.get("bench") == "sort_operands"
                 and "rows_per_sec" in r]
        if sorts:
            print("sort cost vs operands:")
            base = {}
            for r in sorted(sorts, key=lambda r: (r["rows"], r["operands"])):
                key = (r["rows"], "u64x1")
                if r["operands"] == "u64x1":
                    base[r["rows"]] = r["rows_per_sec"]
            for r in sorted(sorts, key=lambda r: (r["rows"], r["operands"])):
                rel = (
                    f"  ({base[r['rows']] / r['rows_per_sec']:.1f}x slower "
                    f"than u64x1)" if r["operands"] != "u64x1"
                    and r["rows"] in base else ""
                )
                print(f"  rows={r['rows']:>9} {r['operands']:>6}: "
                      f"{r['rows_per_sec'] / 1e6:6.1f}M rows/s{rel}")

        lat = [r for r in rs if r.get("bench") == "tunnel_latency"
               and "sec" in r]
        for r in lat:
            print(f"latency {r['metric']}: {r['sec'] * 1000:.2f} ms")
        if lat:
            one = next((r["sec"] for r in lat
                        if r["metric"] == "dispatch_plus_fetch"), None)
            if one:
                print(f"  -> per-query floor ~{one * 1000:.0f} ms: a query "
                      f"must beat the CPU by more than this to win; the "
                      f"fused runner exists to pay it exactly once")

        enc = [r for r in rs if r.get("bench") == "host_encode"
               and "rows_per_sec" in r]
        if enc:
            print("host encode:")
            for r in sorted(enc, key=lambda r: (r["rows"], r["algo"])):
                print(f"  rows={r['rows']:>9} {r['algo']:>12}: "
                      f"{r['rows_per_sec'] / 1e6:6.1f}M rows/s")


if __name__ == "__main__":
    main()
