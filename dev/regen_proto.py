#!/usr/bin/env python
"""Regenerate proto/gen/ballista_pb2.py WITHOUT protoc (descriptor mutation).

The container image ships no protoc, so field additions to
``ballista.proto`` are applied by loading the serialized
FileDescriptorProto embedded in the committed pb2 module, appending the
new FieldDescriptorProtos, and rewriting the module with the mutated
blob.  The mutation list below is the single source of truth for fields
added this way — keep it in sync with ballista.proto (which remains the
human-readable protocol definition).

Idempotent: fields that already exist are skipped.  Run from the repo
root:  python dev/regen_proto.py
"""

from __future__ import annotations

import os
import re
import sys

from google.protobuf import descriptor_pb2

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PB2 = os.path.join(REPO, "arrow_ballista_tpu", "proto", "gen", "ballista_pb2.py")

F = descriptor_pb2.FieldDescriptorProto
# (message, field name, number, type, label)
NEW_FIELDS = [
    ("TaskDefinition", "trace_id", 9, F.TYPE_STRING, F.LABEL_OPTIONAL),
    ("TaskDefinition", "parent_span_id", 10, F.TYPE_STRING, F.LABEL_OPTIONAL),
    ("TaskStatus", "spans_json", 8, F.TYPE_BYTES, F.LABEL_OPTIONAL),
    ("HeartBeatParams", "spans_json", 4, F.TYPE_BYTES, F.LABEL_OPTIONAL),
    # speculative execution + task deadlines (ISSUE 5)
    ("TaskDefinition", "speculative", 11, F.TYPE_BOOL, F.LABEL_OPTIONAL),
    ("TaskDefinition", "timeout_seconds", 12, F.TYPE_DOUBLE, F.LABEL_OPTIONAL),
    ("TaskStatus", "speculative", 9, F.TYPE_BOOL, F.LABEL_OPTIONAL),
    # per-stage speculation rollup survives job completion/persistence
    ("CompletedStageProto", "speculative_launched", 8, F.TYPE_UINT32, F.LABEL_OPTIONAL),
    ("CompletedStageProto", "speculative_wins", 9, F.TYPE_UINT32, F.LABEL_OPTIONAL),
    ("CompletedStageProto", "speculative_wasted", 10, F.TYPE_UINT32, F.LABEL_OPTIONAL),
    # replicated shuffle storage + graceful decommission (ISSUE 6)
    ("ShuffleWritePartition", "replica_path", 6, F.TYPE_STRING, F.LABEL_OPTIONAL),
    ("PartitionLocation", "replica_path", 5, F.TYPE_STRING, F.LABEL_OPTIONAL),
    ("StopExecutorParams", "drain", 4, F.TYPE_BOOL, F.LABEL_OPTIONAL),
    ("StopExecutorParams", "drain_timeout_seconds", 5, F.TYPE_DOUBLE, F.LABEL_OPTIONAL),
    ("ExecutionGraphProto", "external_shuffle_path", 14, F.TYPE_STRING, F.LABEL_OPTIONAL),
    # continuous cluster telemetry (ISSUE 7): per-executor resource
    # snapshot piggybacked on the heartbeat (obs/telemetry.py)
    ("HeartBeatParams", "telemetry_json", 5, F.TYPE_BYTES, F.LABEL_OPTIONAL),
    # adaptive query execution (ISSUE 8): persisted AQE read selections
    # (coalesced/split reduce-task layouts) + policy, so HA adoption and
    # scheduler restart replay the re-planning decisions
    ("UnresolvedShuffleExecNode", "selections_json", 5, F.TYPE_STRING, F.LABEL_OPTIONAL),
    ("ShuffleReaderExecNode", "selections_json", 4, F.TYPE_STRING, F.LABEL_OPTIONAL),
    ("ShuffleReaderExecNode", "source_partition_count", 5, F.TYPE_UINT32, F.LABEL_OPTIONAL),
    ("ExecutionGraphProto", "aqe_settings_json", 15, F.TYPE_STRING, F.LABEL_OPTIONAL),
    # in-flight AQE replan summary survives restart on Unresolved/Resolved
    # stages (Completed stages already persist it inside stage_metrics)
    ("UnResolvedStageProto", "aqe_summary_json", 5, F.TYPE_STRING, F.LABEL_OPTIONAL),
    ("ResolvedStageProto", "aqe_summary_json", 6, F.TYPE_STRING, F.LABEL_OPTIONAL),
    # zero-copy, locality-aware shuffle data plane (ISSUE 10): one
    # multi-partition DoGet per (stage, host) pair instead of N
    # per-partition round trips
    ("FetchPartitionTicket", "paths", 5, F.TYPE_STRING, F.LABEL_REPEATED),
    # multi-tenant admission control (ISSUE 12): a QUEUED job's wire
    # status carries its queue coordinates so the client poll loop can
    # distinguish time-spent-queued from time-spent-running; the graph
    # persists its tenant pool/lane for restart/HA pool accounting
    ("QueuedJob", "queue_position", 1, F.TYPE_UINT32, F.LABEL_OPTIONAL),
    ("QueuedJob", "pool", 2, F.TYPE_STRING, F.LABEL_OPTIONAL),
    ("QueuedJob", "queued_seconds", 3, F.TYPE_DOUBLE, F.LABEL_OPTIONAL),
    ("ExecutionGraphProto", "tenant_json", 16, F.TYPE_STRING, F.LABEL_OPTIONAL),
    # query doctor (ISSUE 13): the status poll can piggyback live
    # progress (per-stage done/running/pending + ETA) and, on demand,
    # the full diagnosis bundle (profile + critical path + findings) so
    # pure-gRPC clients get explain_analyze without a REST round trip
    ("GetJobStatusParams", "include_progress", 2, F.TYPE_BOOL, F.LABEL_OPTIONAL),
    ("GetJobStatusParams", "include_profile", 3, F.TYPE_BOOL, F.LABEL_OPTIONAL),
    ("GetJobStatusResult", "progress_json", 2, F.TYPE_BYTES, F.LABEL_OPTIONAL),
    ("GetJobStatusResult", "profile_json", 3, F.TYPE_BYTES, F.LABEL_OPTIONAL),
    # ...and the job-level timeline anchors persist with the graph, so a
    # decoded (evicted/adopted) job's breakdown keeps the ORIGINAL
    # submit anchor — including failed jobs, which never complete a
    # final stage to stash it in
    ("ExecutionGraphProto", "submitted_unix_us", 17, F.TYPE_UINT64, F.LABEL_OPTIONAL),
    ("ExecutionGraphProto", "planning_us", 18, F.TYPE_UINT64, F.LABEL_OPTIONAL),
    # streaming pipelined execution (ISSUE 15): a reader resolved before
    # its producer completed carries no static locations — it TAILS the
    # scheduler's shuffle-location feed at execution time
    ("ShuffleReaderExecNode", "tail", 6, F.TYPE_BOOL, F.LABEL_OPTIONAL),
    # plan-fingerprint result/shuffle cache (ISSUE 18): cache-served and
    # cache-elided stage ids persist with the graph, so restart/HA
    # adoption keeps skipping the elided subtree instead of waiting
    # forever on inputs nobody will produce
    ("ExecutionGraphProto", "cache_json", 19, F.TYPE_STRING, F.LABEL_OPTIONAL),
    # scheduler crash/failover survival (ISSUE 20): a client-minted
    # idempotency token on ExecuteQuery lets a retried submit (endpoint
    # rotation after UNAVAILABLE) re-attach to the job the first attempt
    # may already have created, instead of double-running it
    ("ExecuteQueryParams", "idempotency_token", 5, F.TYPE_STRING, F.LABEL_OPTIONAL),
    # a scheduler that lost its in-memory executor registry (memory
    # backend restart) answers heartbeats with reregister=true; proto
    # already declares HeartBeatResult.reregister — no mutation needed
]

# Messages added by descriptor mutation (same idempotent scheme as
# NEW_FIELDS): (message name, [(field, number, type, label, type_name)]).
# type_name is required for TYPE_MESSAGE fields and must be fully
# qualified (".ballista_tpu.X").
NEW_MESSAGES = [
    # streaming pipelined execution (ISSUE 15): incremental map-output
    # location deltas.  The scheduler pushes UpdateShuffleLocations to
    # push-mode executors running tailing consumers; pull-mode executors
    # poll GetShuffleLocationDelta.
    (
        "ShuffleLocationDeltaParams",
        [
            ("job_id", 1, F.TYPE_STRING, F.LABEL_OPTIONAL, None),
            ("stage_id", 2, F.TYPE_UINT32, F.LABEL_OPTIONAL, None),
            ("from_index", 3, F.TYPE_UINT32, F.LABEL_OPTIONAL, None),
        ],
    ),
    (
        "ShuffleLocationDelta",
        [
            ("job_id", 1, F.TYPE_STRING, F.LABEL_OPTIONAL, None),
            ("stage_id", 2, F.TYPE_UINT32, F.LABEL_OPTIONAL, None),
            ("from_index", 3, F.TYPE_UINT32, F.LABEL_OPTIONAL, None),
            (
                "locations", 4, F.TYPE_MESSAGE, F.LABEL_REPEATED,
                ".ballista_tpu.PartitionLocation",
            ),
            ("complete", 5, F.TYPE_BOOL, F.LABEL_OPTIONAL, None),
            ("valid", 6, F.TYPE_BOOL, F.LABEL_OPTIONAL, None),
            ("epoch", 7, F.TYPE_UINT32, F.LABEL_OPTIONAL, None),
        ],
    ),
    (
        "UpdateShuffleLocationsParams",
        [
            (
                "deltas", 1, F.TYPE_MESSAGE, F.LABEL_REPEATED,
                ".ballista_tpu.ShuffleLocationDelta",
            ),
        ],
    ),
    (
        "UpdateShuffleLocationsResult",
        [
            ("success", 1, F.TYPE_BOOL, F.LABEL_OPTIONAL, None),
        ],
    ),
]

HEADER = '''# -*- coding: utf-8 -*-
# Generated by the protocol buffer compiler.  DO NOT EDIT!
# source: ballista.proto
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()




DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'ballista_pb2', globals())
# @@protoc_insertion_point(module_scope)
'''


def extract_blob(path: str) -> bytes:
    """Import the committed module and read its FileDescriptor's
    serialized bytes (parsing the literal out of the source is fragile —
    the blob embeds escaped quotes)."""
    sys.path.insert(0, os.path.dirname(path))
    import ballista_pb2  # noqa: PLC0415

    return ballista_pb2.DESCRIPTOR.serialized_pb


def _add_field(msg, fname, number, ftype, label, type_name=None) -> int:
    if any(f.name == fname or f.number == number for f in msg.field):
        return 0
    f = msg.field.add()
    f.name = fname
    f.number = number
    f.type = ftype
    f.label = label
    if type_name:
        f.type_name = type_name
    f.json_name = re.sub(r"_(\w)", lambda m: m.group(1).upper(), fname)
    return 1


def mutate(blob: bytes) -> tuple[bytes, int]:
    fd = descriptor_pb2.FileDescriptorProto()
    fd.ParseFromString(blob)
    by_name = {m.name: m for m in fd.message_type}
    added = 0
    for msg_name, fields in NEW_MESSAGES:
        msg = by_name.get(msg_name)
        if msg is None:
            msg = fd.message_type.add()
            msg.name = msg_name
            by_name[msg_name] = msg
            added += 1
        for fname, number, ftype, label, type_name in fields:
            added += _add_field(msg, fname, number, ftype, label, type_name)
    for msg_name, fname, number, ftype, label in NEW_FIELDS:
        msg = by_name.get(msg_name)
        if msg is None:
            raise SystemExit(f"message {msg_name} not found in descriptor")
        added += _add_field(msg, fname, number, ftype, label)
    return fd.SerializeToString(), added


PROTO = os.path.join(REPO, "arrow_ballista_tpu", "proto", "ballista.proto")


def check() -> None:
    """--check mode (CI): fail when the committed pb2 lacks any NEW_FIELDS
    entry (someone edited this list without re-running the script) or when
    ballista.proto (the human-readable definition) doesn't mention a field
    added by mutation — so proto edits can't land half-regenerated."""
    blob = extract_blob(PB2)
    _, missing = mutate(blob)
    if missing:
        raise SystemExit(
            f"proto drift: {missing} NEW_FIELDS entr(ies) absent from the "
            "committed ballista_pb2.py — run: python dev/regen_proto.py"
        )
    with open(PROTO, encoding="utf-8") as f:
        text = f.read()

    def documented(msg_name: str, fname: str) -> bool:
        # the field must appear as a declaration (`<name> = N`) inside
        # ITS OWN message block — a bare substring match would let
        # `speculative` ride on `speculative_launched`, or credit a
        # field documented in the wrong message
        block = re.search(
            rf"message\s+{msg_name}\s*\{{(.*?)\n\}}", text, re.DOTALL
        )
        if block is None:
            return False
        return re.search(rf"\b{fname}\s*=\s*\d+", block.group(1)) is not None

    undocumented = [
        f"{msg}.{fname}"
        for msg, fname, *_ in NEW_FIELDS
        if not documented(msg, fname)
    ] + [
        f"{msg}.{fname}"
        for msg, fields in NEW_MESSAGES
        for fname, *_ in fields
        if not documented(msg, fname)
    ]
    if undocumented:
        raise SystemExit(
            "proto drift: field(s) generated by descriptor mutation but "
            f"missing from ballista.proto: {', '.join(undocumented)} — "
            "add them to the .proto for humans"
        )
    print("proto check OK: pb2 and ballista.proto agree with NEW_FIELDS")


def main() -> None:
    if "--check" in sys.argv[1:]:
        check()
        return
    blob = extract_blob(PB2)
    new_blob, added = mutate(blob)
    if not added:
        print("descriptor already up to date")
        return
    with open(PB2, "w", encoding="utf-8") as f:
        f.write(HEADER.format(blob=new_blob))
    print(f"added {added} field(s); rewrote {os.path.relpath(PB2, REPO)}")
    # smoke in a FRESH interpreter: this process's default descriptor
    # pool already holds the pre-mutation ballista.proto
    import subprocess  # noqa: PLC0415

    subprocess.run(
        [
            sys.executable,
            "-c",
            "from arrow_ballista_tpu.proto import pb\n"
            "td = pb.TaskDefinition(trace_id='t', parent_span_id='p',\n"
            "                       speculative=True, timeout_seconds=1.5)\n"
            "back = pb.TaskDefinition.FromString(td.SerializeToString())\n"
            "assert back.trace_id == 't' and back.speculative\n"
            "assert abs(back.timeout_seconds - 1.5) < 1e-9\n"
            "ts = pb.TaskStatus(spans_json=b'[]', speculative=True)\n"
            "ts2 = pb.TaskStatus.FromString(ts.SerializeToString())\n"
            "assert ts2.spans_json == b'[]' and ts2.speculative\n"
            "hb = pb.HeartBeatParams(spans_json=b'[]')\n"
            "assert pb.HeartBeatParams.FromString(hb.SerializeToString()).spans_json == b'[]'\n"
            "cs = pb.CompletedStageProto(speculative_launched=2, speculative_wins=1)\n"
            "assert pb.CompletedStageProto.FromString(cs.SerializeToString()).speculative_wins == 1\n"
            "swp = pb.ShuffleWritePartition(path='/a', replica_path='/r')\n"
            "assert pb.ShuffleWritePartition.FromString(swp.SerializeToString()).replica_path == '/r'\n"
            "pl = pb.PartitionLocation(path='/a', replica_path='/r')\n"
            "assert pb.PartitionLocation.FromString(pl.SerializeToString()).replica_path == '/r'\n"
            "se = pb.StopExecutorParams(drain=True, drain_timeout_seconds=7.5)\n"
            "back = pb.StopExecutorParams.FromString(se.SerializeToString())\n"
            "assert back.drain and abs(back.drain_timeout_seconds - 7.5) < 1e-9\n"
            "eg = pb.ExecutionGraphProto(external_shuffle_path='/ext')\n"
            "assert pb.ExecutionGraphProto.FromString(eg.SerializeToString()).external_shuffle_path == '/ext'\n"
            "hb2 = pb.HeartBeatParams(telemetry_json=b'{}', spans_json=b'[]')\n"
            "back = pb.HeartBeatParams.FromString(hb2.SerializeToString())\n"
            "assert back.telemetry_json == b'{}' and back.spans_json == b'[]'\n"
            "us = pb.UnresolvedShuffleExecNode(selections_json='[[[0,0,1]]]')\n"
            "assert pb.UnresolvedShuffleExecNode.FromString(us.SerializeToString()).selections_json == '[[[0,0,1]]]'\n"
            "sr = pb.ShuffleReaderExecNode(selections_json='[]', source_partition_count=8)\n"
            "back = pb.ShuffleReaderExecNode.FromString(sr.SerializeToString())\n"
            "assert back.selections_json == '[]' and back.source_partition_count == 8\n"
            "eg2 = pb.ExecutionGraphProto(aqe_settings_json='{}')\n"
            "assert pb.ExecutionGraphProto.FromString(eg2.SerializeToString()).aqe_settings_json == '{}'\n"
            "up = pb.UnResolvedStageProto(aqe_summary_json='{\"tasks_after\":2}')\n"
            "assert pb.UnResolvedStageProto.FromString(up.SerializeToString()).aqe_summary_json\n"
            "rp = pb.ResolvedStageProto(aqe_summary_json='{\"tasks_after\":2}')\n"
            "assert pb.ResolvedStageProto.FromString(rp.SerializeToString()).aqe_summary_json\n"
            "qj = pb.QueuedJob(queue_position=3, pool='analytics', queued_seconds=1.25)\n"
            "back = pb.QueuedJob.FromString(qj.SerializeToString())\n"
            "assert back.queue_position == 3 and back.pool == 'analytics'\n"
            "assert abs(back.queued_seconds - 1.25) < 1e-9\n"
            "eg3 = pb.ExecutionGraphProto(tenant_json='{\"pool\":\"a\"}')\n"
            "assert pb.ExecutionGraphProto.FromString(eg3.SerializeToString()).tenant_json\n"
            "sd = pb.ShuffleLocationDelta(job_id='j', stage_id=3, from_index=2,\n"
            "                             complete=True, valid=True, epoch=5)\n"
            "sd.locations.add().path = '/a'\n"
            "back = pb.ShuffleLocationDelta.FromString(sd.SerializeToString())\n"
            "assert back.stage_id == 3 and back.epoch == 5 and back.locations[0].path == '/a'\n"
            "up = pb.UpdateShuffleLocationsParams()\n"
            "up.deltas.add().job_id = 'j'\n"
            "assert pb.UpdateShuffleLocationsParams.FromString(up.SerializeToString()).deltas[0].job_id == 'j'\n"
            "dp = pb.ShuffleLocationDeltaParams(job_id='j', stage_id=1, from_index=4)\n"
            "assert pb.ShuffleLocationDeltaParams.FromString(dp.SerializeToString()).from_index == 4\n"
            "srt = pb.ShuffleReaderExecNode(tail=True)\n"
            "assert pb.ShuffleReaderExecNode.FromString(srt.SerializeToString()).tail\n"
            "eq = pb.ExecuteQueryParams(idempotency_token='tok-1')\n"
            "assert pb.ExecuteQueryParams.FromString(eq.SerializeToString()).idempotency_token == 'tok-1'\n"
            "print('round-trip smoke OK')\n",
        ],
        cwd=REPO,
        check=True,
    )


if __name__ == "__main__":
    main()
