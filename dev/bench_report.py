#!/usr/bin/env python
"""Aggregate the repo-root ``BENCH_*.json`` / ``BENCH_SUITE_*.json``
snapshots into one markdown trajectory table (metric x revision).

Every PR's bench runs left a snapshot named after its revision
(``BENCH_r03.json``, ``BENCH_SUITE_r05.json``, ``BENCH_r05_dev.json``
...).  Two shapes exist:

* ``BENCH_<rev>.json`` — a single JSON object whose ``parsed`` field (or
  the object itself) holds one ``{"metric", "value", "unit", ...}``
  record;
* ``BENCH_SUITE_<rev>.json`` — JSON Lines, one record per line.

The report keeps the LAST record per (metric, revision) — suites re-run
a metric to warm caches; the final run is the measurement.  Unknown or
torn lines are skipped, never fatal: this is a reporting tool, and one
corrupt snapshot must not hide the rest of the trajectory.

Usage: ``python dev/bench_report.py [--root DIR]``.  ``dev/tier1.sh
--bench-smoke`` prints it after the smoke benches so the trajectory
rides every bench log.
"""

from __future__ import annotations

import argparse
import json
import os
import re
from typing import Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"^BENCH_(SUITE_)?(?P<rev>r\d+[A-Za-z0-9_]*)\.json$")


def _records_from(path: str) -> List[dict]:
    """Tolerantly extract metric records from one snapshot file."""
    out: List[dict] = []
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return out
    # whole-file JSON object first (BENCH_<rev>.json shape)
    try:
        obj = json.loads(text)
        if isinstance(obj, dict):
            rec = obj.get("parsed", obj)
            if isinstance(rec, dict) and "metric" in rec:
                out.append(rec)
            return out
        if isinstance(obj, list):
            return [r for r in obj if isinstance(r, dict) and "metric" in r]
    except Exception:  # noqa: BLE001 - fall through to JSONL
        pass
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except Exception:  # noqa: BLE001 - torn/garbage line
            continue
        if isinstance(rec, dict) and "metric" in rec:
            out.append(rec)
    return out


def _rev_key(rev: str) -> Tuple[int, str]:
    m = re.match(r"r(\d+)", rev)
    return (int(m.group(1)) if m else 0, rev)


def collect(root: str) -> Tuple[List[str], Dict[str, Dict[str, dict]]]:
    """Scan ``root`` for snapshots; returns (revisions sorted,
    {metric: {revision: last record}})."""
    table: Dict[str, Dict[str, dict]] = {}
    revs: set = set()
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return [], {}
    for name in names:
        m = _NAME_RE.match(name)
        if m is None:
            continue
        rev = m.group("rev")
        records = _records_from(os.path.join(root, name))
        if not records:
            continue
        revs.add(rev)
        for rec in records:
            metric = str(rec.get("metric"))
            table.setdefault(metric, {})[rev] = rec  # last record wins
    return sorted(revs, key=_rev_key), table


def _fmt_value(rec: Optional[dict]) -> str:
    if rec is None:
        return "—"
    v = rec.get("value")
    if isinstance(v, (int, float)):
        s = f"{v:,.2f}".rstrip("0").rstrip(".") if isinstance(v, float) else f"{v:,}"
    else:
        s = str(v)
    vs = rec.get("vs_baseline")
    if isinstance(vs, (int, float)):
        s += f" ({vs:g}x)"
    # plan shape: records from adaptive-execution legs carry the reduce
    # task counts before/after the replan, so the trajectory shows WHAT
    # the speedup bought (64→2 tasks), not just how much
    before, after = rec.get("tasks_before"), rec.get("tasks_after")
    if isinstance(before, int) and isinstance(after, int):
        s += f" [{before}→{after} tasks]"
    # whole-stage fusion records carry the fused plan shape — how many
    # segments the planner cut and how many operators ride one dispatch
    fm = rec.get("fused_metrics")
    if isinstance(fm, dict) and fm.get("fused_segments"):
        s += (
            f" [{fm['fused_segments']} seg · "
            f"{fm.get('fused_ops_per_dispatch', 0)} ops/dispatch]"
        )
    # plan-cache records carry the measured hit rate — the speedup only
    # means something next to how often the cache actually served
    hit_rate = rec.get("hit_rate")
    if isinstance(hit_rate, (int, float)):
        s += f" [hit rate {100 * hit_rate:.0f}%]"
    # wall-clock attribution: the obs leg's record carries the measured
    # job's category breakdown — show where the time went, top two
    breakdown = rec.get("breakdown")
    if isinstance(breakdown, dict):
        top = sorted(
            (
                (k, v)
                for k, v in breakdown.items()
                if isinstance(v, (int, float)) and v > 0
            ),
            key=lambda kv: -kv[1],
        )[:2]
        if top:
            total = sum(
                v for v in breakdown.values() if isinstance(v, (int, float))
            )
            parts = [
                f"{k[:-3].replace('_', ' ')} {100 * v / total:.0f}%"
                for k, v in top
                if total
            ]
            if parts:
                s += f" [{', '.join(parts)}]"
    return s


def markdown_report(root: str = ".") -> str:
    revs, table = collect(root)
    if not table:
        return "(no BENCH_*.json / BENCH_SUITE_*.json snapshots found)"
    lines = [
        "### Benchmark trajectory (metric x revision)",
        "",
        "| metric (unit) | " + " | ".join(revs) + " |",
        "|" + "---|" * (len(revs) + 1),
    ]
    for metric in sorted(table):
        per_rev = table[metric]
        unit = next(
            (r.get("unit") for r in per_rev.values() if r.get("unit")), ""
        )
        label = f"{metric} ({unit})" if unit else metric
        cells = [_fmt_value(per_rev.get(rev)) for rev in revs]
        lines.append("| " + " | ".join([label, *cells]) + " |")
    lines.append("")
    lines.append(
        "_(value (speedup vs baseline); last record per metric per "
        "revision; — = not measured that revision)_"
    )
    return "\n".join(lines)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="directory holding the BENCH_*.json snapshots (default: repo root)",
    )
    args = ap.parse_args()
    print(markdown_report(args.root))


if __name__ == "__main__":
    main()
